"""In-process multi-round-QA workload driver (the reference protocol).

Reusable core for ``bench.py`` and the tuning scripts: N concurrent users
share a system prompt, each keeps a growing ~20k-token chat history, sends
one question per round, Poisson-paced at a target QPS; 100-token answers.
Mirrors the reference harness semantics
(`benchmarks/multi-round-qa/multi-round-qa.py:17-43` WorkloadConfig,
`run_single.sh:12-40` single-accelerator sweep) but steps the engine
directly — no HTTP — so its numbers are the engine's own.

Open-loop measurement: a request's TTFT is charged from its *scheduled*
Poisson arrival, not the submit time, so queueing delay behind a busy
device counts (same as the reference harness).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np


class ProtocolRunner:
    def __init__(
        self,
        engine,
        n_users: int,
        sys_len: int = 1000,
        hist_len: int = 20000,
        question_len: int = 28,
        answer_len: int = 100,
        seed: int = 0,
    ):
        from production_stack_tpu.engine.sequence import SamplingParams

        self._SP = SamplingParams
        self.engine = engine
        self.n_users = n_users
        self.question_len = question_len
        self.answer_len = answer_len
        self.rng = np.random.default_rng(seed)
        self.V = engine.model_cfg.vocab_size
        self.system_prompt = self._toks(sys_len)
        self.histories: List[List[int]] = [
            self.system_prompt + self._toks(hist_len) for _ in range(n_users)
        ]

    def _toks(self, n: int) -> List[int]:
        return self.rng.integers(1, self.V - 1, size=n).tolist()

    def _params(self, max_tokens: int):
        return self._SP(max_tokens=max_tokens, temperature=0.0, ignore_eos=True)

    # ------------------------------------------------------------------

    def drive(
        self,
        requests: List[Tuple[str, int, List[int], int]],
        paced_qps: Optional[float] = None,
        measure_decode: bool = False,
        decode_burst: Optional[int] = None,
    ) -> Tuple[Dict[str, float], Dict[int, List[int]], Optional[float]]:
        """Submit (tag, user, prompt, max_tokens) all at once or at Poisson
        arrival times; step the engine until drained. Returns
        (ttfts by request id, answer tokens by user, decode tok/s or None).

        ``measure_decode`` accumulates time only over steps that produced a
        full decode burst (``decode_burst`` tokens, default
        n_users*num_decode_steps) — the saturated-decode throughput."""
        engine = self.engine
        if decode_burst is None:
            # Saturated-decode qualification: count only full-width,
            # full-depth bursts. With adaptive depth enabled that means
            # DEEP bursts — the shallow ramp before the gate opens spends
            # a whole tunnel round trip on n_users*num_decode_steps tokens
            # and would drag the "saturated" average far below the
            # steady-state rate.
            steps = max(
                engine.cfg.num_decode_steps,
                engine.cfg.adaptive_decode_steps,
                1,
            )
            decode_burst = self.n_users * steps
        # Monotonic: arrival_time feeds Sequence queue/TTFT bookkeeping,
        # which rides time.monotonic() (engine/sequence.py).
        t_base = time.monotonic()
        offset = 0.0
        pending = []
        for req in requests:
            if paced_qps:
                offset += float(self.rng.exponential(1.0 / paced_qps))
            pending.append((t_base + offset, req))
        ttfts: Dict[str, float] = {}
        answers: Dict[int, List[int]] = {}
        dec_toks, dec_time = 0, 0.0
        while pending or engine.has_work():
            now = time.monotonic()
            while pending and pending[0][0] <= now:
                sched, (tag, u, prompt, max_tokens) = pending.pop(0)
                engine.add_request(
                    tag,
                    prompt_token_ids=prompt,
                    sampling=self._params(max_tokens),
                    arrival_time=sched,
                )
            if not engine.has_work():
                time.sleep(max(min(pending[0][0] - time.monotonic(), 0.01), 0.0))
                continue
            ts = time.time()
            outs = engine.step()
            dt = time.time() - ts
            step_toks = 0
            for out in outs:
                step_toks += len(out.new_token_ids)
                u = int(out.request_id.rsplit("-", 1)[1])
                answers.setdefault(u, []).extend(out.new_token_ids)
                if out.ttft is not None and out.request_id not in ttfts:
                    ttfts[out.request_id] = out.ttft
            if measure_decode and step_toks >= decode_burst:
                dec_toks += step_toks
                dec_time += dt
        rate = dec_toks / dec_time if dec_time > 0 else None
        return ttfts, answers, rate

    def qa_round(
        self,
        tag: str,
        users: Optional[List[int]] = None,
        paced_qps: Optional[float] = None,
        measure_decode: bool = False,
        ask: bool = True,
        max_tokens: Optional[int] = None,
        decode_burst: Optional[int] = None,
    ) -> Tuple[List[float], Optional[float]]:
        """One QA round: each user appends a fresh question and requests an
        answer; answers extend the history (multi-round-QA structure)."""
        users = list(range(self.n_users)) if users is None else users
        reqs = []
        for u in users:
            if ask:
                self.histories[u] = self.histories[u] + self._toks(
                    self.question_len
                )
            reqs.append((
                f"{tag}-{u}",
                u,
                self.histories[u],
                self.answer_len if max_tokens is None else max_tokens,
            ))
        ttfts, answers, rate = self.drive(
            reqs, paced_qps=paced_qps, measure_decode=measure_decode,
            decode_burst=decode_burst,
        )
        for u in users:
            self.histories[u] = self.histories[u] + answers.get(u, [])
        return list(ttfts.values()), rate

    # -- canonical phases ----------------------------------------------

    def cold_prefill(self) -> float:
        """Phase 1: every user's full history prefilled (fills the prefix
        cache, compiles the cold buckets). Returns wall seconds."""
        t0 = time.time()
        self.qa_round("cold", ask=False, max_tokens=1)
        return time.time() - t0

    def prefill_probe(self) -> float:
        """Phase 2: one fresh user-sized prompt, warm compiles — prefill
        tok/s over the non-cached suffix. The probe's pages are never
        re-touched afterwards, so later allocation pressure evicts exactly
        them (LRU) rather than any live user history."""
        fresh = self.system_prompt + self._toks(
            len(self.histories[0]) - len(self.system_prompt)
        )
        t0 = time.time()
        self.drive([("fresh-0", 0, fresh, 1)])
        wall = time.time() - t0
        return (len(fresh) - len(self.system_prompt)) / wall

    def warm_compile(self, stagger_groups=((0,), (1, 2), (3, 4, 5, 6), (7,))):
        """Phase 3: all-at-once rounds + a staggered round so every batch
        bucket the Poisson phase can hit is compiled — including the
        adaptive deep-burst shape (its first use must not land inside a
        measured phase: an XLA compile there reads as seconds of fake
        latency)."""
        for r in range(2):
            self.qa_round(f"warmup{r}")
        for group in stagger_groups:
            group = [u for u in group if u < self.n_users]
            if group:
                self.qa_round(f"stagger{group[0]}", users=list(group))
        cfg = self.engine.cfg
        if cfg.adaptive_decode_steps > cfg.num_decode_steps:
            # Force the adaptive gate open so the deep-burst shape
            # DETERMINISTICALLY compiles here (relying on the quiet timer
            # is racy: a fast model can drain the round before it opens).
            # drive() directly — not qa_round — so user histories are NOT
            # extended: measured rounds must start from identical context
            # whether or not the adaptive warm-up ran.
            old = (cfg.adaptive_decode_quiet_s, cfg.adaptive_decode_min_running)
            cfg.adaptive_decode_quiet_s = 0.0
            cfg.adaptive_decode_min_running = 0
            try:
                self.drive([
                    (f"warmdeep-{u}", u, self.histories[u],
                     2 * cfg.adaptive_decode_steps)
                    for u in range(self.n_users)
                ])
            finally:
                cfg.adaptive_decode_quiet_s = old[0]
                cfg.adaptive_decode_min_running = old[1]
        self.engine.allocator.reset_metrics()

    def measured_rounds(
        self, qps: float, n_rounds: int, tag: str = "round"
    ) -> List[float]:
        """Phase 4: Poisson-paced QA rounds; returns all TTFTs."""
        out: List[float] = []
        for r in range(n_rounds):
            ttfts, _ = self.qa_round(f"{tag}{r}", paced_qps=qps)
            out.extend(ttfts)
        return out

    def decode_probe(
        self, max_tokens: int = 96, pipelined: bool = False, burst: int = 32
    ) -> Optional[float]:
        """Phase 5: all users decode concurrently at full context; tok/s
        over full-burst steps.

        ``pipelined`` runs the probe under async decode (one burst always
        in flight, its token fetch overlapped with the next burst's
        execution) — the throughput-serving configuration: the tunnel's
        dispatch→fetch floor (~70-110 ms/burst when synchronous) vanishes
        from the steady state instead of being amortized."""
        import dataclasses as _dc

        if not pipelined:
            _, rate = self.qa_round("probe", measure_decode=True,
                                    max_tokens=max_tokens)
            return rate
        cfg = self.engine.cfg
        sched = self.engine.scheduler
        old = (cfg.async_decode, cfg.num_decode_steps,
               cfg.adaptive_decode_steps, sched.config)
        cfg.async_decode = True
        cfg.num_decode_steps = burst
        cfg.adaptive_decode_steps = 0
        # The in-flight continuation writes one burst past the host view:
        # its pages must be reserved at dispatch time.
        sched.config = _dc.replace(sched.config, decode_lookahead=2,
                                   num_decode_steps=burst)
        try:
            # Warm the burst-start/continue/drain shapes outside the
            # measured window (their first compile would land inside the
            # first qualified burst's dt otherwise).
            self.drive([
                (f"warmpipe-{u}", u, self.histories[u], 2 * burst)
                for u in range(self.n_users)
            ])
            # Qualify at one user short of full width: with the pool sized
            # to ~7.5 of 8 users, one sequence may be parked (KV swap) at
            # any instant — the chip is still saturated.
            _, rate = self.qa_round(
                "probe", measure_decode=True, max_tokens=max_tokens,
                decode_burst=max(self.n_users - 1, 1) * burst,
            )
            return rate
        finally:
            cfg.async_decode, cfg.num_decode_steps = old[0], old[1]
            cfg.adaptive_decode_steps = old[2]
            sched.config = old[3]
