"""Cost-attribution audit phase (bench.py `cost`): do per-request
device-seconds sum to the device-busy wall?

Runs a mixed interactive/batch two-tenant workload to completion on an
in-process engine (tiny model — the attribution math is backend- and
size-independent: shares are exact fractions of each measured dispatch
wall), then checks the acceptance bar from docs/observability.md "Cost
attribution":

- sum of finished requests' attributed device-seconds covers >= 90 % of
  ``ENGINE_TELEMETRY.device_busy_seconds()`` (and never exceeds 110 % —
  over-attribution would mean double-counted pipeline walls);
- ``pst_tenant_device_seconds_total`` splits the two tenants roughly by
  the work offered (the heavy tenant is given ~3x the decode tokens).

Prints ONE JSON object as its last stdout line (bench.py's contract).
Runs BOTH pipeline modes: overlap_decode on (the default hot path,
where double-counting would hide) and off (the parity reference).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(msg: str) -> None:
    print(f"[bench-cost] {msg}", file=sys.stderr, flush=True)


def run_mixed(overlap: bool) -> dict:
    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams
    from production_stack_tpu.obs.engine_telemetry import ENGINE_TELEMETRY

    ENGINE_TELEMETRY.reset_for_tests()
    cfg = EngineConfig(
        model="tiny-llama-debug",
        max_model_len=512,
        block_size=16,
        num_kv_blocks=256,
        max_num_seqs=16,
        overlap_decode=overlap,
        adaptive_decode_min_running=0,
        adaptive_decode_quiet_s=0.0,
        num_decode_steps=4 if overlap else 1,
        cost_attribution=True,
    )
    eng = LLMEngine(cfg)

    def drive(tag: str):
        """One mixed two-tenant pass. acme (interactive) gets short
        generations, batchcorp (batch tier) long ones — ~3x the decode
        tokens, so the tenant meter must split visibly."""
        per_tenant_tokens = {"acme": 0, "batchcorp": 0}
        tenants = {}
        # Batch is heavier on BOTH axes (longer prompts AND ~3x the
        # decode tokens): its chip-time share must come out larger.
        for i in range(6):
            rid = f"{tag}-acme-{i}"
            eng.add_request(
                rid, prompt=f"interactive question {i}",
                sampling=SamplingParams(max_tokens=6, temperature=0.0),
                tenant="acme", tenant_class="interactive",
            )
            tenants[rid] = "acme"
        for i in range(4):
            rid = f"{tag}-batch-{i}"
            eng.add_request(
                rid, prompt=f"batch job {i} " * (3 * i + 4),
                sampling=SamplingParams(max_tokens=27, temperature=0.0),
                tenant="batchcorp", tenant_class="batch",
            )
            tenants[rid] = "batchcorp"
        costs = {}
        while eng.has_work():
            for out in eng.step():
                if out.finished and out.cost is not None:
                    costs[out.request_id] = out.cost
                    per_tenant_tokens[tenants[out.request_id]] += (
                        out.num_output_tokens
                    )
        return costs, tenants, per_tenant_tokens

    # Warm pass first: the measured pass must audit steady-state
    # attribution, not which tenant happened to absorb the XLA compiles
    # (the --require-warm discipline, in miniature).
    drive("warm")
    busy0 = ENGINE_TELEMETRY.device_busy_seconds()
    t0 = time.perf_counter()
    costs, tenants, per_tenant_tokens = drive("run")
    wall = time.perf_counter() - t0

    busy = ENGINE_TELEMETRY.device_busy_seconds() - busy0
    attributed = sum(c["device_s"] for c in costs.values())
    per_tenant_s = {"acme": 0.0, "batchcorp": 0.0}
    for rid, c in costs.items():
        per_tenant_s[tenants[rid]] += c["device_s"]
    frac = attributed / busy if busy > 0 else 0.0
    flight = eng.flight.stats()
    return {
        "mode": "overlap" if overlap else "unpipelined",
        "requests": len(tenants),
        "finished": len(costs),
        "wall_s": round(wall, 3),
        "device_busy_s": round(busy, 4),
        "attributed_device_s": round(attributed, 4),
        "attributed_fraction": round(frac, 4),
        "tenant_device_s": {k: round(v, 4) for k, v in per_tenant_s.items()},
        "tenant_tokens": per_tenant_tokens,
        "flight_steps": flight["total_steps"],
        "kv_page_s_total": round(
            sum(c["kv_page_s"] for c in costs.values()), 3
        ),
    }


def main() -> None:
    results = {}
    for overlap in (False, True):
        mode = "overlap" if overlap else "unpipelined"
        log(f"running mixed two-tenant workload ({mode})")
        results[mode] = run_mixed(overlap)
        log(
            f"{mode}: attributed {results[mode]['attributed_fraction']:.3f} "
            f"of {results[mode]['device_busy_s']:.3f}s device busy"
        )
    # Acceptance (docs/benchmarking.md "The cost phase"): coverage within
    # [0.9, 1.1] in BOTH modes — under-coverage = unattributed device
    # time, over-coverage = double-counted overlap shares — and the heavy
    # tenant is billed more chip time than the light one.
    fracs = [r["attributed_fraction"] for r in results.values()]
    split_ok = all(
        r["tenant_device_s"]["batchcorp"] > r["tenant_device_s"]["acme"]
        for r in results.values()
    )
    out = {
        **results,
        "target_fraction": 0.9,
        "meets_target": bool(
            all(0.9 <= f <= 1.1 for f in fracs) and split_ok
        ),
        "tenant_split_ok": bool(split_ok),
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
