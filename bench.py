"""Benchmark: the reference multi-round-QA protocol on the real chip.

Orchestrates two phases as separate processes (each needs sole chip
ownership) and prints ONE JSON line:

  1. Engine phase (`benchmarks/bench_engine.py`): Llama-3-8B — int8 weights
     + fp8 KV on one 16 GiB v5e chip, the reference's own benchmark model
     (`tutorials/07-benchmark-multi-round-qa-single-gpu.md:5`) — through a
     QPS sweep of the 1000/20000-token protocol with p50/p99 per point,
     plus a saturated decode probe; then llama-1b at the r1-r3 workload for
     round-over-round comparability.
  2. Stack phase: a REAL engine server + the REAL router as subprocesses,
     driven over HTTP by `benchmarks/multi_round_qa.py` — first directly
     against the engine, then through the router. The p50 delta IS the
     router overhead (reference: `router-e2e-test.yml:49-74`).

Headline `value` = p50 TTFT over every measured flagship request across the
sweep; `vs_baseline` = (200 ms north star) / value, >1.0 beats it.
`rpc_floor_ms` records the tunnel's dispatch→fetch floor at run time — the
environment's round-trip latency drifts hour to hour and bounds TTFT below.

This file deliberately never imports jax: the chip is acquired and released
by the child processes.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

TTFT_TARGET_S = 0.200  # north-star p50 TTFT (BASELINE.md)
REPO = os.path.dirname(os.path.abspath(__file__))


def log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def force_cpu() -> bool:
    return (
        os.environ.get("PST_BENCH_CPU") == "1"
        or os.environ.get("JAX_PLATFORMS") == "cpu"
    )


def child_env() -> dict:
    """Environment for chip-owning children. In CPU mode the axon
    sitecustomize must not register the TPU backend (it ignores
    JAX_PLATFORMS), so its trigger var is scrubbed."""
    env = dict(os.environ)
    if force_cpu():
        env["JAX_PLATFORMS"] = "cpu"
        env["PST_FORCE_PALLAS_INTERPRET"] = "1"
        env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def run_engine_phase() -> dict:
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "bench_engine.py")],
        stdout=subprocess.PIPE,
        text=True,
        env=child_env(),
        timeout=int(os.environ.get("PST_BENCH_ENGINE_TIMEOUT", "2400")),
    )
    lines = proc.stdout.strip().splitlines()
    if proc.returncode != 0 or not lines:
        raise RuntimeError(
            f"engine benchmark phase failed (rc={proc.returncode}); "
            "its stderr is above"
        )
    return json.loads(lines[-1])


def ensure_port_free(port: int) -> None:
    import socket

    with socket.socket() as s:
        try:
            s.bind(("127.0.0.1", port))
        except OSError as e:
            raise RuntimeError(
                f"port {port} is already bound (stale bench process?); "
                "kill it before benchmarking — a leftover server would be "
                "silently measured instead of the fresh stack"
            ) from e


def wait_http(url: str, timeout: float, proc=None, log_path=None) -> bool:
    t0 = time.time()
    while time.time() - t0 < timeout:
        if proc is not None and proc.poll() is not None:
            tail = ""
            if log_path and os.path.exists(log_path):
                with open(log_path) as f:
                    tail = "".join(f.readlines()[-15:])
            raise RuntimeError(
                f"server exited early (rc={proc.returncode}):\n{tail}"
            )
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                if r.status == 200:
                    return True
        except Exception:
            time.sleep(1.0)
    return False


def run_stack_phase(on_tpu: bool) -> dict:
    """Engine server + router subprocesses; multi_round_qa over HTTP,
    engine-direct then via-router (same warm workload → delta = router)."""
    from benchmarks.multi_round_qa import WorkloadConfig, run_benchmark, summarize

    # NOTE on lengths: preset models use the byte-fallback tokenizer, so a
    # "word" of synth text is ~6 tokens — the word counts below are ~6x
    # smaller than the intended token counts.
    if on_tpu:
        model = "llama-1b"
        engine_args = [
            "--model", model, "--max-model-len", "8192",
            "--block-size", "64", "--num-kv-blocks", "1024",
            "--max-num-seqs", "16", "--max-num-batched-tokens", "1024",
            "--attn-impl", "pallas", "--kv-cache-dtype", "float8_e4m3fn",
            # One decode width + no adaptive variant: every compiled shape
            # must exist after the warm-up legs — a stray XLA compile
            # during a measured leg would read as seconds of fake "TTFT".
            "--num-decode-steps", "4", "--min-decode-bucket", "4",
        ]
        # Light load on purpose: this phase isolates ROUTER OVERHEAD (the
        # p50 delta). Engine server + router + client share one host core;
        # a saturating workload measures host contention, not the router.
        sys_len, hist_len, answer_len = 120, 300, 16  # ≈ 700+1.8k byte toks
        start_timeout = 420.0
    else:
        model = "tiny-llama-debug"
        engine_args = [
            "--model", model, "--max-model-len", "2048", "--block-size", "8",
            "--num-kv-blocks", "2100", "--max-num-seqs", "8",
            "--max-num-batched-tokens", "128", "--attn-impl", "gather",
            "--num-decode-steps", "4", "--min-decode-bucket", "4",
        ]
        sys_len, hist_len, answer_len = 32, 64, 8  # ≈ 200+400 byte tokens
        start_timeout = 180.0

    eport, rport = 18200, 18201
    ensure_port_free(eport)
    ensure_port_free(rport)
    elog, rlog = "/tmp/pst_bench_engine.log", "/tmp/pst_bench_router.log"
    engine = subprocess.Popen(
        [sys.executable, "-m", "production_stack_tpu.engine.server",
         "--port", str(eport), *engine_args],
        stdout=open(elog, "w"), stderr=subprocess.STDOUT,
        cwd=REPO, env=child_env(),
    )
    router = None
    try:
        if not wait_http(
            f"http://127.0.0.1:{eport}/health", start_timeout,
            proc=engine, log_path=elog,
        ):
            raise RuntimeError("engine server did not become healthy")
        router = subprocess.Popen(
            [sys.executable, "-m", "production_stack_tpu.router.app",
             "--port", str(rport),
             "--service-discovery", "static",
             "--static-backends", f"http://127.0.0.1:{eport}",
             "--static-models", model,
             "--routing-logic", "roundrobin"],
            stdout=open(rlog, "w"), stderr=subprocess.STDOUT,
            cwd=REPO,
        )
        if not wait_http(
            f"http://127.0.0.1:{rport}/health", 60,
            proc=router, log_path=rlog,
        ):
            raise RuntimeError("router did not become healthy")

        def drive(base_url: str, tag: str, rounds: int) -> dict:
            cfg = WorkloadConfig(
                num_users=4, num_rounds=rounds, qps=1.0,
                system_prompt_len=sys_len, chat_history_len=hist_len,
                answer_len=answer_len, model=model, base_url=base_url,
                seed=7,  # same histories both legs: second leg runs warm
            )
            t0 = time.time()
            records = asyncio.run(run_benchmark(cfg))
            s = summarize(records, time.time() - t0)
            log(f"stack[{tag}]: {s}")
            return s

        # Warm-up legs cover BOTH rounds the measured legs replay (greedy
        # answers are deterministic, so round-1 prompts repeat exactly):
        # otherwise the direct leg would pay cold prefills + XLA compiles
        # the via-router leg then inherits warm, biasing the delta low.
        # The second pass catches any bucket the first pass's arrival
        # pattern missed.
        drive(f"http://127.0.0.1:{eport}", "warmup", rounds=2)
        drive(f"http://127.0.0.1:{eport}", "warmup2", rounds=2)
        # Interleaved legs with MEDIANS: the tunnel's TTFT floor both
        # drifts (tens of ms/minute) and throws multi-second one-sided
        # transients; a mean over two direct legs let a single transient
        # flip the delta's sign. Alternating D/V legs and taking medians
        # keeps one bad leg from biasing either side.
        import statistics

        direct_legs, via_legs = [], []
        for i in range(3):
            direct_legs.append(
                drive(f"http://127.0.0.1:{eport}", f"direct-{i}", rounds=2)
            )
            via_legs.append(
                drive(f"http://127.0.0.1:{rport}", f"via-{i}", rounds=2)
            )
        direct_p50 = round(
            statistics.median(leg["ttft_p50_ms"] for leg in direct_legs), 1
        )
        via_p50 = round(
            statistics.median(leg["ttft_p50_ms"] for leg in via_legs), 1
        )
        return {
            "model": model,
            "engine_direct_p50_ttft_ms": direct_p50,
            "via_router_p50_ttft_ms": via_p50,
            "router_overhead_ms": round(via_p50 - direct_p50, 1),
            "engine_direct_legs": direct_legs,
            "via_router_legs": via_legs,
        }
    finally:
        for proc in (router, engine):
            if proc is not None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()


def probe_backend() -> str:
    proc = subprocess.run(
        [sys.executable, "-c", "import jax; print(jax.default_backend())"],
        stdout=subprocess.PIPE, text=True, env=child_env(), timeout=120,
    )
    return proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "cpu"


def main() -> None:
    if os.environ.get("PST_BENCH_SKIP_ENGINE") == "1":  # stack-only debug
        engine_res = {"backend": probe_backend()}
    else:
        engine_res = run_engine_phase()
    backend = engine_res.get("backend", "unknown")
    on_tpu = backend == "tpu"

    stack = None
    if os.environ.get("PST_BENCH_SKIP_STACK") != "1":
        try:
            stack = run_stack_phase(on_tpu)
        except Exception as e:  # noqa: BLE001 — stack numbers are additive
            log(f"stack phase failed: {e}")
            stack = {"error": str(e)}

    flag = engine_res.get("flagship", {})
    p50 = flag.get("p50_ttft_ms")
    out = {
        "metric": "p50_ttft_warm",
        "value": p50,
        "unit": "ms",
        "vs_baseline": (
            round(TTFT_TARGET_S * 1e3 / p50, 3) if p50 else None
        ),
        "backend": backend,
        "rpc_floor_ms": engine_res.get("rpc_floor_ms"),
        **{k: v for k, v in flag.items() if k != "p50_ttft_ms"},
        "llama_1b": engine_res.get("llama_1b"),
        "stack": stack,
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
