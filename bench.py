"""Benchmark: the reference multi-round-QA protocol on the real chip.

Mirrors the reference's single-accelerator benchmark protocol
(`benchmarks/multi-round-qa/run_single.sh:12-40`, BASELINE.md): N concurrent
users sharing a 1000-token system prompt, each with a 20,000-token chat
history, Poisson request arrivals, 100-token answers, 32k max_model_len.
Runs the real engine (continuous batching, paged KV at 32k, prefix caching,
double-buffered pallas kernels on TPU) directly — no HTTP — so the number is
the engine's, not the socket stack's.

Phases:
  1. cold    — every user's full history is prefilled (max_tokens=1),
               filling the prefix cache and compiling the cold buckets.
  2. probe   — one fresh 21k-token prompt, timed → **prefill tok/s**
               (caches warm, compiles done).
  3. warm-compile — two all-at-once QA rounds plus a staggered round so
               every batch bucket the Poisson phase can hit is compiled.
  4. measure — 3 QA rounds with Poisson arrivals at the protocol QPS;
               **p50/p99 warm TTFT** over all measured requests.
  5. decode probe — all users decode concurrently at full context; steps
               that are full decode bursts give **decode tok/s/chip**.

Prints ONE JSON line; progress goes to stderr.
  metric       p50 TTFT for warm rounds (prefix-cached system prompt+history)
  vs_baseline  (north-star p50 TTFT target 200 ms) / measured — >1.0 beats it
  extra fields: p99 TTFT, prefill/decode tok/s + MFU, hit rate, workload dims
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

TTFT_TARGET_S = 0.200  # north-star p50 TTFT (BASELINE.md)
V5E_PEAK_FLOPS = 197e12  # bf16 peak of one v5e chip (MXU)


def log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams

    backend = jax.default_backend()
    on_tpu = backend == "tpu"

    if on_tpu:
        # llama-1b at the full protocol: 8 users x ~21k context, everything
        # HBM-resident (8 x 21.8k tokens x 64 KiB/token ≈ 10.7 GiB KV next
        # to 1.66 GiB params on a 16 GiB v5e).
        cfg = EngineConfig(
            model="llama-1b",
            max_model_len=32768,
            block_size=128,  # fewer, larger page DMAs for the 20k contexts
            num_kv_blocks=1408,  # 180k tokens of KV (~11 GiB)
            max_num_seqs=16,
            max_prefill_tokens=1024,
            attn_impl="pallas",
            # fp8 KV is the headline serving configuration (reported in the
            # output JSON): halves KV bytes, +27% decode throughput and
            # ~25ms better p50 TTFT measured vs bf16 at this protocol
            # (137ms/1.46 vs 161ms/1.24). Override with
            # PST_BENCH_KV_DTYPE=bfloat16 for the full-precision number.
            kv_cache_dtype=(
                os.environ.get("PST_BENCH_KV_DTYPE") or "float8_e4m3fn"
            ),
            # At the protocol QPS the system runs near decode saturation
            # (1 req/s x 100-token answers ~= the chip's long-context decode
            # rate), so TTFT is dominated by decode throughput, which on
            # this dispatch-latency-heavy setup is maximized by longer
            # bursts (fewer host syncs per token): n=4 beats both n<=2 and
            # the pipelined mode here.
            num_decode_steps=4,
            min_decode_bucket=8,  # one decode shape across the Poisson phase
        )
        n_users, sys_len, hist_len = 8, 1000, 20000
        question_len, answer_len = 28, 100
        qps = 1.0  # top of the reference single-accelerator sweep (0.1-1.1)
    else:  # CPU smoke fallback so the bench is runnable anywhere
        cfg = EngineConfig(
            model="tiny-llama-debug",
            max_model_len=512,
            block_size=8,
            num_kv_blocks=512,
            max_num_seqs=8,
            max_prefill_tokens=128,
            attn_impl="gather",
            num_decode_steps=4,
            min_decode_bucket=4,
        )
        n_users, sys_len, hist_len = 4, 64, 96
        question_len, answer_len = 12, 16
        qps = 8.0

    t0 = time.time()
    engine = LLMEngine(cfg)
    n_params = engine.runner.param_count
    log(f"engine up in {time.time()-t0:.1f}s, {n_params/1e9:.2f}B params")

    rng = np.random.default_rng(0)
    V = engine.model_cfg.vocab_size
    system_prompt = rng.integers(1, V - 1, size=sys_len).tolist()
    histories = [
        system_prompt + rng.integers(1, V - 1, size=hist_len).tolist()
        for _ in range(n_users)
    ]

    def params_for(max_tokens):
        return SamplingParams(
            max_tokens=max_tokens, temperature=0.0, ignore_eos=True
        )

    decode_burst = n_users * cfg.num_decode_steps

    def drive(requests, paced_qps=None, measure_decode=False):
        """Submit (tag, user, prompt, max_tokens) — all at once or at
        Poisson-spaced arrival times — and step the engine until drained.
        Returns (ttfts, answers, decode_rate)."""
        t_base = time.time()
        offset = 0.0
        pending = []
        for req in requests:
            if paced_qps:
                offset += float(rng.exponential(1.0 / paced_qps))
            pending.append((t_base + offset, req))
        ttfts, answers = {}, {}
        dec_toks, dec_time = 0, 0.0
        while pending or engine.has_work():
            now = time.time()
            while pending and pending[0][0] <= now:
                # arrival_time is the SCHEDULED Poisson arrival, not the
                # submit time: a request whose slot passed while a device
                # step was in flight must still be charged that queueing
                # delay (open-loop measurement, like the reference harness).
                sched, (tag, u, prompt, max_tokens) = pending.pop(0)
                engine.add_request(
                    tag, prompt_token_ids=prompt,
                    sampling=params_for(max_tokens), arrival_time=sched,
                )
            if not engine.has_work():
                time.sleep(max(min(pending[0][0] - time.time(), 0.01), 0.0))
                continue
            ts = time.time()
            outs = engine.step()
            dt = time.time() - ts
            step_toks = 0
            for out in outs:
                step_toks += len(out.new_token_ids)
                u = int(out.request_id.rsplit("-", 1)[1])
                answers.setdefault(u, []).extend(out.new_token_ids)
                if out.ttft is not None and out.request_id not in ttfts:
                    ttfts[out.request_id] = out.ttft
            if measure_decode and step_toks >= decode_burst:
                dec_toks += step_toks
                dec_time += dt
        rate = dec_toks / dec_time if dec_time > 0 else None
        return ttfts, answers, rate

    def qa_round(tag, users=None, paced_qps=None, measure_decode=False,
                 ask=True, max_tokens=None):
        """One QA round: each user appends a fresh question and requests an
        answer; sampled answers extend the history (the multi-round-QA
        structure of the reference benchmark)."""
        users = list(range(n_users)) if users is None else users
        reqs = []
        for u in users:
            if ask:
                histories[u] = histories[u] + rng.integers(
                    1, V - 1, size=question_len
                ).tolist()
            reqs.append((
                f"{tag}-{u}", u, histories[u],
                answer_len if max_tokens is None else max_tokens,
            ))
        ttfts, answers, rate = drive(
            reqs, paced_qps=paced_qps, measure_decode=measure_decode
        )
        for u in users:
            histories[u] = histories[u] + answers.get(u, [])
        return list(ttfts.values()), rate

    # Phase 1: cold prefill of every user's full history.
    t0 = time.time()
    prompt_tokens = sum(len(h) for h in histories)
    qa_round("cold", ask=False, max_tokens=1)
    log(f"cold: {prompt_tokens} tokens in {time.time()-t0:.1f}s "
        f"(incl. compiles)")

    # Phase 2: prefill throughput, compiles done: a fresh user-sized prompt.
    # The shared system prompt is a prefix hit; count computed tokens only.
    fresh = system_prompt + rng.integers(1, V - 1, size=hist_len).tolist()
    t0 = time.time()
    drive([("fresh-0", 0, fresh, 1)])
    prefill_wall = time.time() - t0
    prefill_tok_s = (len(fresh) - sys_len) / prefill_wall
    log(f"prefill probe: {len(fresh)-sys_len} tokens in {prefill_wall:.1f}s "
        f"({prefill_tok_s:.0f} tok/s)")

    # Phase 3: warm-compile — all-at-once rounds, then a staggered round so
    # the B∈{1,2,4} warm-chunk buckets the Poisson phase hits are compiled.
    for r in range(2):
        qa_round(f"warmup{r}")
    for group in ([0], [1, 2], [3, 4, 5, 6], [7]):
        qa_round(f"stagger{group[0]}", users=group)
    engine.allocator.reset_metrics()
    log("warm-compile rounds done")

    # Phase 4: measured rounds at the protocol's Poisson pacing. Four
    # rounds (32 requests): host/tunnel timing jitter is ±25-45 ms on this
    # box, so more samples stabilize the recorded p50.
    all_ttfts = []
    t0 = time.time()
    for r in range(4):
        ttfts, _ = qa_round(f"round{r}", paced_qps=qps)
        all_ttfts.extend(ttfts)
        log(f"round {r}: p50 so far "
            f"{np.percentile(all_ttfts, 50)*1e3:.1f} ms")
    measure_wall = time.time() - t0

    # Phase 5: decode probe — all users decode concurrently at full context.
    _, decode_tok_s = qa_round("probe", measure_decode=True, max_tokens=96)

    p50 = float(np.percentile(all_ttfts, 50))
    p99 = float(np.percentile(all_ttfts, 99))
    mfu = lambda r: round(2 * n_params * r / V5E_PEAK_FLOPS, 4) if r else None
    print(
        json.dumps(
            {
                "metric": "p50_ttft_warm",
                "value": round(p50 * 1000, 2),
                "unit": "ms",
                "vs_baseline": round(TTFT_TARGET_S / p50, 3),
                "p99_ttft_ms": round(p99 * 1000, 2),
                "prefill_tok_per_s": round(prefill_tok_s, 1),
                "prefill_mfu": mfu(prefill_tok_s),
                "decode_tok_per_s_chip": round(decode_tok_s, 1)
                if decode_tok_s else None,
                "decode_mfu": mfu(decode_tok_s),
                "prefix_cache_hit_rate": round(engine.allocator.hit_rate, 3),
                "model": engine.model_cfg.name,
                "kv_cache_dtype": str(cfg.kv_cache_dtype or engine.model_cfg.dtype),
                "backend": backend,
                "n_users": n_users,
                "system_prompt_tokens": sys_len,
                "history_tokens": hist_len,
                "max_model_len": cfg.max_model_len,
                "qps": qps,
                "n_measured_requests": len(all_ttfts),
                "measure_wall_s": round(measure_wall, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
