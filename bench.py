"""Benchmark: multi-round-QA-shaped serving workload on the real chip.

Mirrors the reference's benchmark protocol (`benchmarks/multi-round-qa/
multi-round-qa.py:17-43`, see BASELINE.md): N users sharing a system prompt,
per-user history that grows round over round, measuring TTFT and generation
throughput. Runs the real engine (continuous batching, paged KV, prefix
caching, pallas decode kernel on TPU) directly — no HTTP — so the number is
the engine's, not the socket stack's.

Prints ONE JSON line:
  metric       p50 TTFT for warm rounds (prefix-cached system prompt+history)
  vs_baseline  (north-star p50 TTFT target 200 ms) / measured — >1.0 beats it
  extra fields: decode throughput tok/s/chip, prefix hit rate, model, backend
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams

    backend = jax.default_backend()
    on_tpu = backend == "tpu"

    if on_tpu:
        cfg = EngineConfig(
            model="llama-1b",
            max_model_len=4096,
            block_size=32,
            num_kv_blocks=1536,  # 48k tokens of KV (~3 GiB) next to 2.5 GiB params
            max_num_seqs=16,
            max_prefill_tokens=1024,
            attn_impl="pallas",
            num_decode_steps=8,  # burst decode: amortize dispatch latency
        )
        n_users, sys_len, hist_len, answer_len = 8, 256, 512, 64
    else:  # CPU smoke fallback so the bench is runnable anywhere
        cfg = EngineConfig(
            model="tiny-llama-debug",
            max_model_len=512,
            block_size=8,
            num_kv_blocks=512,
            max_num_seqs=8,
            max_prefill_tokens=128,
            attn_impl="gather",
            num_decode_steps=4,
        )
        n_users, sys_len, hist_len, answer_len = 4, 64, 96, 16

    engine = LLMEngine(cfg)
    rng = np.random.default_rng(0)
    V = engine.model_cfg.vocab_size
    system_prompt = rng.integers(1, V - 1, size=sys_len).tolist()
    histories = [
        system_prompt + rng.integers(1, V - 1, size=hist_len).tolist()
        for _ in range(n_users)
    ]
    question_len = 32
    sp = SamplingParams(max_tokens=answer_len, temperature=0.0, ignore_eos=True)

    def run_round(tag: str):
        """One QA round per user: history + fresh question → answer. The
        answer (actual sampled tokens) is appended to the history, exactly
        the multi-round-QA structure of the reference benchmark."""
        for u in range(n_users):
            histories[u] = histories[u] + rng.integers(
                1, V - 1, size=question_len
            ).tolist()
        t_submit = time.time()
        for u in range(n_users):
            engine.add_request(f"{tag}-{u}", prompt_token_ids=histories[u],
                               sampling=sp, arrival_time=t_submit)
        ttfts, answers, n_tokens = {}, {u: [] for u in range(n_users)}, 0
        while engine.has_work():
            for out in engine.step():
                n_tokens += len(out.new_token_ids)
                u = int(out.request_id.rsplit("-", 1)[1])
                answers[u].extend(out.new_token_ids)
                if out.num_output_tokens == 1:
                    ttfts[out.request_id] = out.ttft
        wall = time.time() - t_submit
        for u in range(n_users):
            histories[u] = histories[u] + answers[u]
        return list(ttfts.values()), n_tokens, wall

    # Warmup: two rounds — the first is cold (big prefill buckets + cache
    # fill), the second compiles the warm-round bucket shapes (short chunk
    # prefill + the decode table widths measurement rounds will use).
    run_round("warmup0")
    run_round("warmup1")
    engine.allocator.reset_metrics()

    # Warm rounds: the multi-round regime the reference optimizes for
    # (system prompt + history prefix-cached; BASELINE.md hit-rate target).
    all_ttfts, total_tokens, total_wall = [], 0, 0.0
    for r in range(3):
        ttfts, n_tok, wall = run_round(f"round{r}")
        all_ttfts.extend(ttfts)
        total_tokens += n_tok
        total_wall += wall

    p50 = float(np.percentile(all_ttfts, 50))
    p99 = float(np.percentile(all_ttfts, 99))
    tok_per_s = total_tokens / total_wall
    target_s = 0.200  # north-star p50 TTFT (BASELINE.md)
    print(
        json.dumps(
            {
                "metric": "p50_ttft_warm",
                "value": round(p50 * 1000, 2),
                "unit": "ms",
                "vs_baseline": round(target_s / p50, 3),
                "p99_ttft_ms": round(p99 * 1000, 2),
                "decode_tok_per_s_chip": round(tok_per_s, 1),
                "prefix_cache_hit_rate": round(engine.allocator.hit_rate, 3),
                "model": engine.model_cfg.name,
                "backend": backend,
                "n_users": n_users,
            }
        )
    )


if __name__ == "__main__":
    main()
