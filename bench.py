"""Benchmark: the reference multi-round-QA protocol on the real chip.

Orchestrates three phases as separate processes (the engine phases need
sole chip ownership) and prints ONE JSON line:

  1. Engine phase (`benchmarks/bench_engine.py`): Llama-3-8B, int4
     group-wise weights (Pallas streaming matmul) + fp8 KV on one 16 GiB
     v5e chip. Two sub-phases: a 4-user TTFT sweep (6 QPS points
     0.1-1.1, ≥300 measured requests, per-point p50/p99 + RPC floor +
     drift-corrected TTFT — the workload must FIT so TTFT measures the
     engine, not eviction thrash) and an 8-users-×-20k CONCURRENCY phase
     (more live KV than HBM holds; live-KV swap rotates the overflow)
     ending in a pipelined-deep-burst saturated decode probe; then
     llama-1b for round-over-round comparability.
  2. Stack phase: a REAL engine server + the REAL router as subprocesses;
     router overhead as the mean ± 95% CI of PAIRED per-request deltas
     (same warm prompt direct vs via-router, order alternating) over
     ≥200 pairs (reference: `router-e2e-test.yml:49-74`).
  3. Fleet phase: multi-round QA through the real router over FOUR fake
     engines, fleet KV hit rate read via the router's own scrape parser —
     the fused `fleet` policy vs the paired round-robin baseline, plus a
     churn leg (one engine SIGKILLed mid-phase) against the ≥0.9 target.

Headline `value` = p50 TTFT over every measured flagship request across the
sweep; `vs_baseline` = (200 ms north star) / value, >1.0 beats it.
`rpc_floor_ms` records the tunnel's dispatch→fetch floor at run time — the
environment's round-trip latency drifts hour to hour and bounds TTFT below.

This file deliberately never imports jax: the chip is acquired and released
by the child processes.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

TTFT_TARGET_S = 0.200  # north-star p50 TTFT (BASELINE.md)
REPO = os.path.dirname(os.path.abspath(__file__))

# A run with NO budget is how r05 died: the driver's `timeout` landed
# mid-bring-up with nothing flushed. Every run is budgeted now — an
# explicit --time-budget wins, else these defaults (just under the
# historical 3600 s driver wall; --tiny is the CPU smoke profile).
DEFAULT_TIME_BUDGET_S = 3300.0
TINY_TIME_BUDGET_S = 240.0
WATCHDOG_LEAD_S = 30.0


class BenchInterrupted(BaseException):
    """Raised by the SIGTERM/SIGALRM handlers so an externally imposed
    wall (the driver's `timeout`, or --time-budget) unwinds the current
    phase THROUGH its cleanup finallys and still reaches the final
    emit(). BaseException on purpose: the per-phase `except Exception`
    guards must not swallow it into an ordinary phase error."""


class TimeBudget:
    """Total wall budget carved into per-phase walls (ROADMAP 5a: the
    r05 run died on rc:124 with nothing parseable — a budgeted run
    truncates phases deliberately instead of being killed mid-write).

    ``phase_wall(weight, weights_left)`` hands the next phase its share
    of whatever remains; a phase that finishes early donates the slack
    to the rest. 0/None = unbudgeted (the historical behavior)."""

    def __init__(self, total: float = 0.0) -> None:
        self.total = max(float(total or 0.0), 0.0)
        self.t0 = time.monotonic()

    @property
    def enabled(self) -> bool:
        return self.total > 0

    def remaining(self) -> float:
        return max(self.total - (time.monotonic() - self.t0), 0.0)

    def phase_wall(self, weight: float, weights_left: float) -> float:
        """Seconds granted to the next phase: its weight share of the
        remaining budget."""
        return self.remaining() * weight / max(weights_left, weight)

    def exhausted(self, floor: float = 20.0) -> bool:
        """Too little budget left to produce a meaningful phase."""
        return self.enabled and self.remaining() < floor


def install_term_trap() -> None:
    """SIGTERM (the driver's `timeout` sends it before SIGKILL) raises
    BenchInterrupted in the main thread: the current phase unwinds
    through its process-cleanup finallys and main() flushes the final
    JSON — an rc:124 run still yields a parseable result."""
    def _raise(signum, frame):
        raise BenchInterrupted(f"signal {signum}")

    signal.signal(signal.SIGTERM, _raise)
    signal.signal(signal.SIGALRM, _raise)


def phase_alarm(seconds: float) -> None:
    """Arm the per-phase wall (0 disarms): SIGALRM -> BenchInterrupted."""
    signal.setitimer(signal.ITIMER_REAL, max(seconds, 0.0))


def log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def force_cpu() -> bool:
    return (
        os.environ.get("PST_BENCH_CPU") == "1"
        or os.environ.get("JAX_PLATFORMS") == "cpu"
    )


def child_env() -> dict:
    """Environment for chip-owning children. In CPU mode the axon
    sitecustomize must not register the TPU backend (it ignores
    JAX_PLATFORMS), so its trigger var is scrubbed."""
    env = dict(os.environ)
    if force_cpu():
        env["JAX_PLATFORMS"] = "cpu"
        env["PST_FORCE_PALLAS_INTERPRET"] = "1"
        env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def read_partial(path: str) -> dict:
    """Best-effort read of an incrementally-written partial result file
    (bench_engine.write_partial); {} when absent or unparseable."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def run_engine_phase() -> dict:
    """Run the engine benchmark subprocess.

    The child checkpoints its cumulative result to $PST_BENCH_ENGINE_OUT
    after every qps point and phase, so a timeout (BENCH_r05: rc=124 with
    nothing parseable) or crash degrades to the partial result instead of
    losing the whole run — recompile-heavy sweeps stay attributable.
    """
    partial_path = os.environ.get(
        "PST_BENCH_ENGINE_OUT", "/tmp/pst_bench_engine_partial.json"
    )
    env = child_env()
    env["PST_BENCH_ENGINE_OUT"] = partial_path
    # The child persists flight snapshots here so a tail outlier stays
    # explainable even when the child is SIGKILLed (post-mortem path).
    env["PST_BENCH_FLIGHT_SNAPSHOT_DIR"] = engine_snapshot_dir()
    try:
        os.remove(partial_path)  # never serve a previous run's partial
    except OSError:
        pass
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "benchmarks", "bench_engine.py")],
            stdout=subprocess.PIPE,
            text=True,
            env=env,
            timeout=int(os.environ.get("PST_BENCH_ENGINE_TIMEOUT", "4200")),
        )
    except subprocess.TimeoutExpired:
        partial = read_partial(partial_path)
        if partial:
            log("engine phase timed out; continuing with its partial result")
            partial["partial"] = True
            partial["error"] = "engine phase timed out"
            return partial
        raise
    lines = proc.stdout.strip().splitlines()
    if lines:
        try:
            parsed = json.loads(lines[-1])
        except ValueError:
            parsed = None
        if not isinstance(parsed, dict) or "backend" not in parsed:
            # Stray non-object JSON, or a JSON-ish log line that is not the
            # bench result (every real result carries "backend"): fall
            # through to the partial checkpoint.
            parsed = None
        if parsed is not None:
            if proc.returncode != 0:
                # A complete result with a nonzero rc is deliberate
                # (--require-warm failing on compile pollution): keep the
                # data, surface the verdict.
                parsed["engine_rc"] = proc.returncode
            return parsed
    partial = read_partial(partial_path)
    if partial:
        log(f"engine phase failed (rc={proc.returncode}); "
            "continuing with its partial result")
        partial["partial"] = True
        partial["error"] = f"engine phase rc={proc.returncode}"
        return partial
    raise RuntimeError(
        f"engine benchmark phase failed (rc={proc.returncode}); "
        "its stderr is above"
    )


def run_cost_phase() -> dict:
    """Cost-attribution audit (benchmarks/bench_cost.py): per-request
    device-seconds must sum to within 10% of the device-busy wall in
    BOTH pipeline modes, and the heavy tenant must be billed more chip
    time (docs/observability.md "Cost attribution"). Runs the tiny model
    in a subprocess — the attribution math is share-exact and therefore
    backend-independent, so this phase never needs the chip."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "bench_cost.py")],
        stdout=subprocess.PIPE,
        text=True,
        env=child_env(),
        timeout=int(os.environ.get("PST_BENCH_COST_TIMEOUT", "600")),
    )
    lines = proc.stdout.strip().splitlines()
    if proc.returncode != 0 or not lines:
        raise RuntimeError(f"cost phase failed (rc={proc.returncode})")
    return json.loads(lines[-1])


def ensure_port_free(port: int) -> None:
    import socket

    with socket.socket() as s:
        try:
            s.bind(("127.0.0.1", port))
        except OSError as e:
            raise RuntimeError(
                f"port {port} is already bound (stale bench process?); "
                "kill it before benchmarking — a leftover server would be "
                "silently measured instead of the fresh stack"
            ) from e


def wait_http(url: str, timeout: float, proc=None, log_path=None) -> bool:
    t0 = time.time()
    while time.time() - t0 < timeout:
        if proc is not None and proc.poll() is not None:
            tail = ""
            if log_path and os.path.exists(log_path):
                with open(log_path) as f:
                    tail = "".join(f.readlines()[-15:])
            raise RuntimeError(
                f"server exited early (rc={proc.returncode}):\n{tail}"
            )
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                if r.status == 200:
                    return True
        except Exception:
            time.sleep(1.0)
    return False


def paired_router_overhead(
    direct_url: str,
    router_url,
    model: str,
    sys_len: int,
    hist_len: int,
    n_pairs: int = 220,
) -> dict:
    """Mean ± 95% CI of per-request router overhead over paired requests.

    Each pair streams the SAME (warm, prefix-cached) prompt once direct to
    the engine and once through the router, back to back, order alternating
    pair to pair; TTFT is client-measured time to the first SSE byte. The
    per-pair delta cancels engine compute and the tunnel floor (both legs
    of a pair see the same drift window), isolating the router hop —
    reference methodology: router-e2e-test.yml's direct-vs-router compare,
    upgraded from aggregate medians to a paired design.

    ``router_url`` may be a list of replica URLs (the ``replicas: 2``
    variant): via-router legs round-robin across them, the way an LB
    spreads clients, so the measured overhead includes the shared-state
    backend's cost on the hot path.
    """
    import statistics

    import aiohttp

    router_urls = (
        list(router_url) if isinstance(router_url, (list, tuple))
        else [router_url]
    )

    rng = __import__("random").Random(11)
    prompts = [
        " ".join(
            "w%d" % rng.randrange(5000) for _ in range(sys_len + hist_len)
        )
        for _ in range(16)
    ]

    async def ttft(session: "aiohttp.ClientSession", base: str, prompt: str) -> float:
        t0 = time.perf_counter()
        async with session.post(
            f"{base}/v1/completions",
            json={
                "model": model, "prompt": prompt, "max_tokens": 4,
                "temperature": 0.0, "stream": True,
            },
        ) as resp:
            resp.raise_for_status()
            async for _ in resp.content.iter_any():
                return time.perf_counter() - t0
        raise RuntimeError("empty stream")

    async def run() -> dict:
        deltas: list = []
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=120)
        ) as session:
            for p in prompts:  # warm both paths everywhere
                await ttft(session, direct_url, p)
                for r in router_urls:
                    await ttft(session, r, p)
            for i in range(n_pairs):
                p = prompts[i % len(prompts)]
                via = router_urls[i % len(router_urls)]
                if i % 2 == 0:
                    d = await ttft(session, direct_url, p)
                    v = await ttft(session, via, p)
                else:
                    v = await ttft(session, via, p)
                    d = await ttft(session, direct_url, p)
                deltas.append((v - d) * 1e3)
        mean = statistics.fmean(deltas)
        sd = statistics.stdev(deltas)
        ci = 1.96 * sd / (len(deltas) ** 0.5)
        return {
            "router_overhead_ms": round(mean, 2),
            "router_overhead_ci95_ms": round(ci, 2),
            "router_overhead_median_ms": round(statistics.median(deltas), 2),
            "n_pairs": len(deltas),
            "overhead_significant": bool(abs(mean) > ci),
        }

    return asyncio.run(run())


def run_stack_phase(on_tpu: bool) -> dict:
    """Engine server + router subprocesses; multi_round_qa over HTTP,
    engine-direct then via-router (same warm workload → delta = router)."""
    from benchmarks.multi_round_qa import WorkloadConfig, run_benchmark, summarize

    # NOTE on lengths: preset models use the byte-fallback tokenizer, so a
    # "word" of synth text is ~6 tokens — the word counts below are ~6x
    # smaller than the intended token counts.
    if on_tpu:
        model = "llama-1b"
        engine_args = [
            "--model", model, "--max-model-len", "8192",
            "--block-size", "64", "--num-kv-blocks", "1024",
            "--max-num-seqs", "16", "--max-num-batched-tokens", "1024",
            "--attn-impl", "pallas", "--kv-cache-dtype", "float8_e4m3fn",
            # One decode width + no adaptive variant: every compiled shape
            # must exist after the warm-up legs — a stray XLA compile
            # during a measured leg would read as seconds of fake "TTFT".
            "--num-decode-steps", "4", "--min-decode-bucket", "4",
        ]
        # Light load on purpose: this phase isolates ROUTER OVERHEAD (the
        # p50 delta). Engine server + router + client share one host core;
        # a saturating workload measures host contention, not the router.
        sys_len, hist_len, answer_len = 120, 300, 16  # ≈ 700+1.8k byte toks
        start_timeout = 420.0
    else:
        model = "tiny-llama-debug"
        engine_args = [
            "--model", model, "--max-model-len", "2048", "--block-size", "8",
            "--num-kv-blocks", "2100", "--max-num-seqs", "8",
            "--max-num-batched-tokens", "128", "--attn-impl", "gather",
            "--num-decode-steps", "4", "--min-decode-bucket", "4",
        ]
        sys_len, hist_len, answer_len = 32, 64, 8  # ≈ 200+400 byte tokens
        start_timeout = 180.0

    eport, rport = 18200, 18201
    ensure_port_free(eport)
    ensure_port_free(rport)
    elog, rlog = "/tmp/pst_bench_engine.log", "/tmp/pst_bench_router.log"
    engine = subprocess.Popen(
        [sys.executable, "-m", "production_stack_tpu.engine.server",
         "--port", str(eport), *engine_args],
        stdout=open(elog, "w"), stderr=subprocess.STDOUT,
        cwd=REPO, env=child_env(),
    )
    router = None
    replicas = []
    try:
        if not wait_http(
            f"http://127.0.0.1:{eport}/health", start_timeout,
            proc=engine, log_path=elog,
        ):
            raise RuntimeError("engine server did not become healthy")
        router = subprocess.Popen(
            [sys.executable, "-m", "production_stack_tpu.router.app",
             "--port", str(rport),
             "--service-discovery", "static",
             "--static-backends", f"http://127.0.0.1:{eport}",
             "--static-models", model,
             "--routing-logic", "roundrobin"],
            stdout=open(rlog, "w"), stderr=subprocess.STDOUT,
            cwd=REPO,
        )
        if not wait_http(
            f"http://127.0.0.1:{rport}/health", 60,
            proc=router, log_path=rlog,
        ):
            raise RuntimeError("router did not become healthy")

        def drive(base_url: str, tag: str, rounds: int) -> dict:
            cfg = WorkloadConfig(
                num_users=4, num_rounds=rounds, qps=1.0,
                system_prompt_len=sys_len, chat_history_len=hist_len,
                answer_len=answer_len, model=model, base_url=base_url,
                seed=7,  # same histories both legs: second leg runs warm
            )
            t0 = time.time()
            records = asyncio.run(run_benchmark(cfg))
            s = summarize(records, time.time() - t0)
            log(f"stack[{tag}]: {s}")
            return s

        # One short leg sanity-checks the stack end to end (and compiles
        # the decode buckets its concurrency hits); the paired phase warms
        # its OWN prompts before measuring, so no further warm-up is
        # needed for the delta to be unbiased.
        drive(f"http://127.0.0.1:{eport}", "sanity", rounds=1)
        # Paired per-request deltas (r4 verdict: the leg-median sandwich
        # produced a negative, noise-dominated number): each PAIR sends the
        # SAME warm prompt direct and via the router back-to-back, with the
        # order alternating pair to pair so tunnel drift cancels within
        # each drift window; the statistic is the mean per-pair delta with
        # a 95% CI over >=200 pairs.
        pairs = paired_router_overhead(
            f"http://127.0.0.1:{eport}", f"http://127.0.0.1:{rport}",
            model, sys_len, hist_len,
            n_pairs=int(os.environ.get("PST_BENCH_PAIRS", "220")),
        )

        # replicas: 2 variant (ROADMAP item 5's ≤ +5 ms p50 gate): the
        # same paired design against TWO router replicas coordinating
        # over the gossip state backend, clients alternating replicas
        # like an LB would. The single-replica router is stopped first —
        # three routers contending for the shared host core would measure
        # scheduling noise, not the replication cost.
        router.send_signal(signal.SIGTERM)
        try:
            router.wait(timeout=10)
        except subprocess.TimeoutExpired:
            router.kill()
        router = None
        r2ports = [rport + 1, rport + 2]
        for p in r2ports:
            ensure_port_free(p)
        r2logs = []
        for i, p in enumerate(r2ports):
            lg = f"/tmp/pst_bench_router_r2_{i}.log"
            r2logs.append(lg)
            replicas.append(subprocess.Popen(
                [sys.executable, "-m", "production_stack_tpu.router.app",
                 "--port", str(p),
                 "--service-discovery", "static",
                 "--static-backends", f"http://127.0.0.1:{eport}",
                 "--static-models", model,
                 "--routing-logic", "roundrobin",
                 "--state-backend", "gossip",
                 "--state-peers",
                 f"http://127.0.0.1:{r2ports[1 - i]}",
                 "--state-sync-interval", "0.25",
                 "--state-replica-id", f"bench-replica-{i}"],
                stdout=open(lg, "w"), stderr=subprocess.STDOUT,
                cwd=REPO,
            ))
        for p, proc, lg in zip(r2ports, replicas, r2logs):
            if not wait_http(f"http://127.0.0.1:{p}/ready", 60,
                             proc=proc, log_path=lg):
                raise RuntimeError(f"router replica :{p} not ready")
        pairs2 = paired_router_overhead(
            f"http://127.0.0.1:{eport}",
            [f"http://127.0.0.1:{p}" for p in r2ports],
            model, sys_len, hist_len,
            n_pairs=int(os.environ.get("PST_BENCH_PAIRS_R2", "120")),
        )
        delta_p50 = round(
            pairs2["router_overhead_median_ms"]
            - pairs["router_overhead_median_ms"], 2,
        )
        replicas2 = {
            "replicas": 2,
            **pairs2,
            "p50_delta_vs_single_ms": delta_p50,
            "target_ms": 5.0,
            "meets_target": bool(delta_p50 <= 5.0),
        }
        if not replicas2["meets_target"]:
            log(f"replicas:2 router overhead p50 delta {delta_p50}ms "
                "exceeds the +5ms target")
        return {"model": model, **pairs, "replicas2": replicas2}
    finally:
        for proc in [router, engine] + replicas:
            if proc is not None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()


def run_fleet_phase() -> dict:
    """Fleet routing hit rate THROUGH the routing path (ROADMAP item 3's
    acceptance): multi-round QA through the real router over FOUR fake
    engines, hit rate read from each engine's /metrics via the router's
    own scrape parser. Fake engines (with the derived KV/prefix-cache
    simulation) — the ROUTING POLICY, not chip speed, is under test; four
    of them make affinity-vs-spread differences visible in a way two real
    CPU engines never were. Three paired legs in the SAME run:

      fleet_hit_rate  — --routing-logic fleet, no faults (≥ 0.9 target)
      rr_hit_rate     — naive roundrobin baseline (fleet must beat it)
      churn_hit_rate  — fleet again with one engine SIGKILLed mid-phase;
                        breakers fence the corpse, failover re-homes its
                        sessions, the trie relearns — hit rate must stay
                        ≥ 0.9 (the churn-tolerance acceptance gate)
    """
    from benchmarks.multi_round_qa import WorkloadConfig, run_benchmark
    from production_stack_tpu.router.stats.engine_stats import EngineStats

    model = "fake/model"
    n_engines = 4
    env = dict(os.environ, PYTHONPATH=REPO)

    def measure(policy: str, base_port: int, churn_kill_after: float = 0.0) -> dict:
        eports = [base_port + i for i in range(n_engines)]
        rport = base_port + n_engines
        for p in eports + [rport]:
            ensure_port_free(p)
        procs = []
        logs = []
        try:
            for i, p in enumerate(eports):
                lg = f"/tmp/pst_fleet_engine_{p}.log"
                logs.append(lg)
                procs.append(subprocess.Popen(
                    [sys.executable, "-m",
                     "production_stack_tpu.testing.fake_engine",
                     "--port", str(p), "--model", model,
                     "--speed", "120", "--ttft", "0.02",
                     "--name", f"fleet-{i}",
                     # Small enough that roundrobin (every conversation
                     # cached on every engine, ~21k tokens) thrashes,
                     # while affinity (2-3 conversations per engine,
                     # ~5-7k tokens) fits comfortably.
                     "--kv-capacity-tokens", "12000"],
                    stdout=open(lg, "w"), stderr=subprocess.STDOUT,
                    cwd=REPO, env=env,
                ))
            for p, proc, lg in zip(eports, procs, logs):
                if not wait_http(f"http://127.0.0.1:{p}/health", 60,
                                 proc=proc, log_path=lg):
                    raise RuntimeError(f"fleet fake engine :{p} not healthy")
            rlog = f"/tmp/pst_fleet_router_{policy}_{base_port}.log"
            router = subprocess.Popen(
                [sys.executable, "-m", "production_stack_tpu.router.app",
                 "--port", str(rport),
                 "--service-discovery", "static",
                 "--static-backends",
                 ",".join(f"http://127.0.0.1:{p}" for p in eports),
                 "--static-models", ",".join([model] * n_engines),
                 "--routing-logic", policy,
                 "--engine-stats-interval", "1",
                 "--proxy-retries", "3", "--retry-backoff", "0.01",
                 "--breaker-failure-threshold", "2",
                 "--breaker-recovery-time", "60"],
                stdout=open(rlog, "w"), stderr=subprocess.STDOUT,
                cwd=REPO, env=env,
            )
            procs.append(router)
            if not wait_http(f"http://127.0.0.1:{rport}/health", 60,
                             proc=router, log_path=rlog):
                raise RuntimeError("fleet router not healthy")
            cfg = WorkloadConfig(
                num_users=8, num_rounds=32, qps=4.0,
                system_prompt_len=24, chat_history_len=800, answer_len=8,
                model=model, base_url=f"http://127.0.0.1:{rport}", seed=13,
            )

            killed_port = None

            async def drive() -> list:
                nonlocal killed_port
                bench_task = asyncio.ensure_future(run_benchmark(cfg))
                if churn_kill_after > 0:
                    done, _ = await asyncio.wait(
                        [bench_task], timeout=churn_kill_after
                    )
                    if not done:
                        # SIGKILL, no drain, no goodbye: the churn leg.
                        procs[0].kill()
                        killed_port = eports[0]
                        log(f"fleet[{policy}]: killed engine :{killed_port} "
                            f"mid-phase at t={churn_kill_after:.1f}s")
                return await bench_task

            t0 = time.time()
            records = asyncio.run(drive())
            wall = time.time() - t0
            ok = sum(1 for r in records if r.status == 200)
            hits = queries = 0.0
            per_engine = []
            for p in eports:
                if p == killed_port:
                    continue  # the corpse serves no /metrics
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{p}/metrics", timeout=10
                ) as r:
                    # The router's own scrape parser — the path KV-aware
                    # routing relies on in production.
                    st = EngineStats.from_vllm_scrape(r.read().decode())
                hits += st.gpu_prefix_cache_hits_total
                queries += st.gpu_prefix_cache_queries_total
                per_engine.append({
                    "engine": p,
                    "hit_rate": round(st.gpu_prefix_cache_hit_rate, 3),
                })
            rate = hits / queries if queries else 0.0
            out = {"policy": policy, "fleet_hit_rate": round(rate, 3),
                   "requests_ok": ok, "requests_total": len(records),
                   "wall_seconds": round(wall, 1),
                   "per_engine": per_engine}
            if churn_kill_after > 0:
                out["killed_engine"] = killed_port
            return out
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.send_signal(signal.SIGTERM)
            for proc in procs:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()

    fleet = measure("fleet", 18300)
    rr = measure("roundrobin", 18310)
    # Kill one engine mid-phase: halfway through the no-churn leg's wall.
    churn = measure("fleet", 18320,
                    churn_kill_after=max(fleet["wall_seconds"] * 0.55, 2.0))
    return {
        "fleet_hit_rate": fleet["fleet_hit_rate"],
        "rr_hit_rate": rr["fleet_hit_rate"],
        "churn_hit_rate": churn["fleet_hit_rate"],
        "fleet": fleet,
        "roundrobin": rr,
        "churn": churn,
        "engines": n_engines,
        "target_hit_rate": 0.9,
        # Churn tolerance is BOTH numbers: the survivors' hit rate AND
        # near-zero client-visible failures (a broken failover path must
        # not pass just because the corpse's metrics are excluded).
        "meets_target": (
            fleet["fleet_hit_rate"] >= 0.9
            and churn["fleet_hit_rate"] >= 0.9
            and churn["requests_ok"] >= 0.98 * churn["requests_total"]
        ),
        "beats_roundrobin": (
            fleet["fleet_hit_rate"] > rr["fleet_hit_rate"]
            and churn["fleet_hit_rate"] > rr["fleet_hit_rate"]
        ),
    }


def run_autoscale_phase() -> dict:
    """Closed-loop autoscale under surge (docs/autoscaling.md): the REAL
    router (k8s discovery against the in-process fake API server) + the
    REAL pst-operator actuator + fake engines, with offered load DOUBLED
    mid-run. Measures how long the loop takes to absorb the surge, the
    client p99 while absorbing, that the new replica comes up with ZERO
    fresh compiles (warm-start path), and the wake→first-token bound of a
    scaled-to-zero pool. Kill-surviving like every stack phase: the
    subprocess fleet dies in the finally, partial numbers ride the emit."""
    from production_stack_tpu.testing.fake_k8s import PST, FakeK8s

    operator_dir = os.path.join(REPO, "operator")
    operator_bin = os.path.join(operator_dir, "build", "pst-operator")
    build = subprocess.run(["make"], cwd=operator_dir,
                           capture_output=True, text=True)
    if build.returncode != 0 or not os.path.exists(operator_bin):
        return {"error": f"operator build failed: {build.stderr[-400:]}"}

    model = "fake/model"
    slo_ms = float(os.environ.get("PST_BENCH_AUTOSCALE_SLO_MS", "1500"))
    env = dict(os.environ, PYTHONPATH=REPO)

    def operator_tick(api: str) -> None:
        proc = subprocess.run(
            [operator_bin, "--api-server", api, "--namespace", "default",
             "--once"],
            capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            raise RuntimeError(f"operator tick failed: {proc.stderr[-300:]}")

    def get_json(url: str) -> dict:
        with urllib.request.urlopen(url, timeout=10) as r:
            return json.loads(r.read().decode())

    def compile_total(eng_url: str) -> float:
        with urllib.request.urlopen(f"{eng_url}/metrics", timeout=5) as r:
            text = r.read().decode()
        return sum(float(line.rsplit(" ", 1)[1])
                   for line in text.splitlines()
                   if line.startswith("pst_engine_compile_total"))

    def pct(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return vals[min(int(round(q * (len(vals) - 1))), len(vals) - 1)]

    def seed_runtime(k8s, autoscale):
        k8s.seed(PST, "tpuruntimes", {
            "apiVersion": "pst.production-stack.io/v1alpha1",
            "kind": "TPURuntime",
            "metadata": {"name": "base", "namespace": "default"},
            "spec": {"model": model, "replicas": 1, "engineConfig": {},
                     "kvCache": {}, "autoscale": autoscale},
        })

    def start_engine(k8s, procs, engines, idx, eport, ip_base):
        ip = f"127.0.0.{ip_base + idx}"
        name = f"base-engine-{idx}"
        lg = f"/tmp/pst_autoscale_engine_{ip_base + idx}.log"
        p = subprocess.Popen(
            [sys.executable, "-m",
             "production_stack_tpu.testing.fake_engine",
             "--host", ip, "--port", str(eport), "--model", model,
             "--speed", "2000", "--name", name],
            stdout=open(lg, "w"), stderr=subprocess.STDOUT,
            cwd=REPO, env=env)
        procs.append(p)
        url = f"http://{ip}:{eport}"
        if not wait_http(f"{url}/health", 60, proc=p, log_path=lg):
            raise RuntimeError(f"autoscale fake engine {name} not healthy")
        engines[name] = url
        k8s.seed_engine_pod(name, eport, ip=ip)
        return name

    def start_router(k8s, procs, eport, rport, tag):
        lg = f"/tmp/pst_autoscale_router_{tag}.log"
        p = subprocess.Popen(
            [sys.executable, "-m", "production_stack_tpu.router.app",
             "--host", "127.0.0.1", "--port", str(rport),
             "--service-discovery", "k8s",
             "--k8s-label-selector", "model=base",
             "--k8s-port", str(eport),
             "--routing-logic", "roundrobin",
             "--engine-stats-interval", "1",
             "--slo-ttft-ms", "40", "--admission-rate", "400",
             "--proxy-retries", "0", "--breaker-failure-threshold", "100"],
            stdout=open(lg, "w"), stderr=subprocess.STDOUT, cwd=REPO,
            env=dict(env, PST_K8S_API_SERVER=k8s.url))
        procs.append(p)
        if not wait_http(f"http://127.0.0.1:{rport}/health", 60,
                         proc=p, log_path=lg):
            raise RuntimeError("autoscale router not healthy")
        k8s.seed_router_replica("pst-router", rport)
        return f"http://127.0.0.1:{rport}"

    def wait_signal(router_url, pred, timeout_s, what):
        deadline = time.time() + timeout_s
        sig = None
        while time.time() < deadline:
            sig = get_json(f"{router_url}/autoscale/signal")
            if pred(sig):
                return sig
            time.sleep(0.3)
        raise RuntimeError(f"autoscale signal never converged ({what}): {sig}")

    # ---- surge leg: offered load doubles against a saturating pool ------
    eport, rport = 18400, 18409
    for p in (eport, rport):
        ensure_port_free(p)
    k8s = FakeK8s().start()
    procs = []
    engines = {}
    records = []  # (t_done, latency_ms, served_by, ok)
    rec_lock = threading.Lock()
    stop_load = threading.Event()
    workers = []
    out = {"slo_ms": slo_ms}
    try:
        start_engine(k8s, procs, engines, 0, eport, ip_base=2)
        router_url = start_router(k8s, procs, eport, rport, "surge")
        seed_runtime(k8s, {"minReplicas": 1, "maxReplicas": 3,
                           "scaleDownStabilizationS": 3600,
                           "idleVerdicts": 3})
        wait_signal(router_url, lambda s: s["engines_ready"] == 1, 30,
                    "initial discovery")

        def worker(idx):
            i = 0
            while not stop_load.is_set():
                t0 = time.time()
                try:
                    req = urllib.request.Request(
                        f"{router_url}/v1/completions",
                        data=json.dumps({
                            "model": model, "prompt": f"load-{idx}-{i}",
                            "max_tokens": 2}).encode(),
                        headers={"Content-Type": "application/json"},
                        method="POST")
                    with urllib.request.urlopen(req, timeout=30) as resp:
                        by = resp.headers.get("X-Served-By")
                        resp.read()
                    ok = True
                except Exception:  # noqa: BLE001 — shed/failure is a datum
                    by, ok = None, False
                with rec_lock:
                    records.append(
                        (time.time(), (time.time() - t0) * 1e3, by, ok))
                i += 1
                time.sleep(0.05)

        def add_workers(n):
            for _ in range(n):
                t = threading.Thread(target=worker, args=(len(workers),),
                                     daemon=True)
                workers.append(t)
                t.start()

        add_workers(2)          # baseline offered load
        time.sleep(3.0)
        # Surge: the lone engine saturates (120ms >> the 40ms objective)
        # AND the offered load doubles.
        req = urllib.request.Request(
            f"{engines['base-engine-0']}/admin/fail",
            data=json.dumps({"mode": "slow", "delay": 0.12,
                             "count": -1}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=5):
            pass
        surge_start = time.time()
        add_workers(2)
        sig = wait_signal(router_url, lambda s: s["replica_hint"] >= 2, 45,
                          "surge hint")
        out["surge_hint"] = sig["replica_hint"]
        operator_tick(k8s.url)
        st = k8s.bucket(PST, "tpuruntimes")["base"].get("status", {})
        if st.get("lastAutoscaleAction") != "scale_up":
            raise RuntimeError(f"operator never scaled up: {st}")
        want = int(st["desiredReplicas"])
        new_names = [
            start_engine(k8s, procs, engines, i, eport, ip_base=2)
            for i in range(1, want)
        ]
        compile_before = {n: compile_total(engines[n]) for n in new_names}
        # Absorbed: a new replica serves live traffic.
        absorb_deadline = time.time() + 60
        absorb_end = None
        while absorb_end is None and time.time() < absorb_deadline:
            with rec_lock:
                tail = records[-20:]
            if any(by in new_names for _, _, by, _ in tail):
                absorb_end = time.time()
            else:
                time.sleep(0.2)
        if absorb_end is None:
            raise RuntimeError("new replica never took traffic")
        time.sleep(2.0)         # post-absorb sample window
        stop_load.set()
        for t in workers:
            t.join(timeout=30)
        cold = sum(compile_total(engines[n]) - compile_before[n]
                   for n in new_names)
        with rec_lock:
            absorb_window = [r for r in records if r[0] >= surge_start]
        p99 = pct([ms for _, ms, _, ok in absorb_window if ok], 0.99)
        failed = sum(1 for *_, ok in absorb_window if not ok)
        out.update({
            "absorb_seconds": round(absorb_end - surge_start, 2),
            "p99_during_absorb_ms": round(p99, 1) if p99 else None,
            "cold_compiles_on_new_replicas": cold,
            "replicas_after": want,
            "requests_during_absorb": len(absorb_window),
            "failed_during_absorb": failed,
        })
    finally:
        stop_load.set()
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        k8s.stop()

    # ---- wake leg: a fresh pool parks slept, first arrival wakes it -----
    # Fresh fleet on purpose: the surge leg's burn windows keep its hint
    # high for minutes, which is exactly the anti-flap conservatism the
    # actuator encodes — waiting them out would blow the phase wall.
    eport2, rport2 = 18410, 18419
    for p in (eport2, rport2):
        ensure_port_free(p)
    k8s = FakeK8s().start()
    procs = []
    engines = {}
    try:
        start_engine(k8s, procs, engines, 0, eport2, ip_base=21)
        router_url = start_router(k8s, procs, eport2, rport2, "wake")
        seed_runtime(k8s, {"minReplicas": 1, "maxReplicas": 2,
                           "scaleDownStabilizationS": 0, "idleVerdicts": 1,
                           "scaleToZero": True})
        wait_signal(router_url, lambda s: s["engines_ready"] == 1
                    and s["in_flight_total"] == 0, 30, "wake-leg discovery")
        operator_tick(k8s.url)
        st = k8s.bucket(PST, "tpuruntimes")["base"].get("status", {})
        if st.get("lastAutoscaleAction") != "sleep":
            raise RuntimeError(f"pool never parked slept: {st}")
        t0 = time.time()
        req = urllib.request.Request(
            f"{router_url}/v1/completions",
            data=json.dumps({"model": model, "prompt": "wake",
                             "max_tokens": 4, "stream": True}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=60) as resp:
            resp.read(16)       # first streamed token bytes
            wake_s = time.time() - t0
            resp.read()
        out["wake_to_first_token_s"] = round(wake_s, 3)
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        k8s.stop()

    out["meets_target"] = bool(
        out.get("absorb_seconds") is not None
        and out.get("p99_during_absorb_ms") is not None
        and out["p99_during_absorb_ms"] <= slo_ms
        and out.get("cold_compiles_on_new_replicas") == 0
        and out.get("failed_during_absorb") == 0
        and out.get("wake_to_first_token_s") is not None
        and out["wake_to_first_token_s"] < 10.0
    )
    return out


def run_tenant_phase() -> dict:
    """Tenant flood isolation (docs/multi-tenancy.md): the real router
    with --tenant-isolation over two fake engines; a victim tenant paces
    steady traffic while a flooder offers ~10x its admitted share. The
    headline numbers are the victim's p50/p99 with and without the flood
    and the isolation delta — the ≤10% guarantee BENCH rounds capture as
    driver evidence (per-point, kill-surviving, like the fleet phase).
    """
    model = "fake/model"
    env = dict(os.environ, PYTHONPATH=REPO)
    base_port = 18400
    eports = [base_port, base_port + 1]
    rport = base_port + 2
    for p in eports + [rport]:
        ensure_port_free(p)
    tenant_file = "/tmp/pst_bench_tenants.json"
    with open(tenant_file, "w") as f:
        json.dump({"tenants": {
            "victim": {"weight": 1, "tier": "interactive"},
            "flooder": {"weight": 1, "tier": "interactive"},
        }}, f)
    procs = []
    try:
        for i, p in enumerate(eports):
            lg = f"/tmp/pst_tenant_engine_{p}.log"
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "production_stack_tpu.testing.fake_engine",
                 "--port", str(p), "--model", model,
                 "--speed", "40", "--ttft", "0.02",
                 "--name", f"tenant-{i}"],
                stdout=open(lg, "w"), stderr=subprocess.STDOUT,
                cwd=REPO, env=env,
            ))
            if not wait_http(f"http://127.0.0.1:{p}/health", 60,
                             proc=procs[-1], log_path=lg):
                raise RuntimeError(f"tenant fake engine :{p} not healthy")
        rlog = "/tmp/pst_tenant_router.log"
        router = subprocess.Popen(
            [sys.executable, "-m", "production_stack_tpu.router.app",
             "--port", str(rport),
             "--service-discovery", "static",
             "--static-backends",
             ",".join(f"http://127.0.0.1:{p}" for p in eports),
             "--static-models", ",".join([model] * len(eports)),
             "--routing-logic", "roundrobin",
             "--engine-stats-interval", "1",
             "--tenant-isolation",
             "--tenant-config", tenant_file,
             "--admission-rate", "30",
             "--admission-queue-timeout", "0.3"],
            stdout=open(rlog, "w"), stderr=subprocess.STDOUT,
            cwd=REPO, env=env,
        )
        procs.append(router)
        if not wait_http(f"http://127.0.0.1:{rport}/health", 60,
                         proc=router, log_path=rlog):
            raise RuntimeError("tenant router not healthy")

        import aiohttp

        base = f"http://127.0.0.1:{rport}"
        engine_urls = [f"http://127.0.0.1:{p}" for p in eports]
        collector = forensics_collector()
        stall_injected = os.environ.get("PST_BENCH_INJECT_STALL") == "1"
        if stall_injected:
            # CI's induced r05 signature: a one-shot N-ms decode stall on
            # the first engine — the victim leg's p99 blows past 3x its
            # p50 and the collector below must harvest a bundle naming
            # the stalled bucket + queue state.
            stall_s = float(os.environ.get("PST_BENCH_STALL_S", "1.5"))
            req = urllib.request.Request(
                f"{engine_urls[0]}/admin/fail",
                data=json.dumps({"mode": "stall", "delay": stall_s,
                                 "count": 1}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                resp.read()
            log(f"tenants: armed one-shot {stall_s}s stall on engine 0")
        metrics_baseline = collector.mark(engine_urls + [base])

        async def one(session, tenant, max_tokens=4):
            t0 = time.monotonic()
            async with session.post(
                f"{base}/v1/completions",
                json={"model": model, "prompt": f"{tenant} q",
                      "max_tokens": max_tokens},
                headers={"X-PST-Tenant": tenant},
            ) as resp:
                await resp.read()
                return resp.status, time.monotonic() - t0

        async def victim_phase(session, n=40, pace=0.05):
            lat, shed = [], 0
            for _ in range(n):
                status, dt = await one(session, "victim")
                if status == 200:
                    lat.append(dt)
                else:
                    shed += 1
                await asyncio.sleep(pace)
            return lat, shed

        async def drive() -> dict:
            async with aiohttp.ClientSession() as session:
                baseline, base_shed = await victim_phase(session)
                stop = asyncio.Event()

                async def flood():
                    tasks = []
                    while not stop.is_set():
                        tasks.append(asyncio.create_task(
                            one(session, "flooder", max_tokens=1)
                        ))
                        await asyncio.sleep(0.01)  # ~100 rps offered
                    done = await asyncio.gather(
                        *tasks, return_exceptions=True
                    )
                    return [d[0] for d in done if isinstance(d, tuple)]

                flood_task = asyncio.create_task(flood())
                await asyncio.sleep(0.3)
                flooded, flood_shed = await victim_phase(session)
                stop.set()
                statuses = await flood_task
                return {
                    "baseline": baseline, "flooded": flooded,
                    "victim_sheds": base_shed + flood_shed,
                    "flood_offered": len(statuses),
                    "flood_shed": sum(1 for s in statuses if s == 429),
                }

        res = asyncio.run(drive())

        def pct(samples, q):
            if not samples:
                return None
            ordered = sorted(samples)
            return ordered[min(int(len(ordered) * q), len(ordered) - 1)]

        base_p99 = pct(res["baseline"], 0.99)
        flood_p99 = pct(res["flooded"], 0.99)
        delta = (
            (flood_p99 - base_p99) / base_p99
            if base_p99 and flood_p99 else None
        )
        # Tail forensics while the stack is still alive: a leg whose p99
        # blows past 3x its p50 (the injected stall, or a real isolation
        # failure) harvests flight snapshots, worst traces, fleet state
        # and metrics deltas into the run's evidence dir.
        evidence = []
        for leg, samples in (("baseline", res["baseline"]),
                             ("flooded", res["flooded"])):
            p50_s, p99_s = pct(samples, 0.5), pct(samples, 0.99)
            bundle = collector.maybe_collect(
                "tenants", leg,
                p50_s * 1e3 if p50_s else None,
                p99_s * 1e3 if p99_s else None,
                engines=engine_urls, router=base,
                baseline=metrics_baseline,
                detail={"stall_injected": stall_injected},
            )
            if bundle:
                evidence.append(bundle)
                log(f"forensics: tenants/{leg} tail bar crossed "
                    f"-> {bundle}")
        return {
            "evidence_bundles": evidence,
            "victim_p50_ms": round(pct(res["baseline"], 0.5) * 1e3, 1),
            "victim_p99_ms": round(base_p99 * 1e3, 1),
            "flood_victim_p50_ms": round(pct(res["flooded"], 0.5) * 1e3, 1),
            "flood_victim_p99_ms": round(flood_p99 * 1e3, 1),
            "p99_delta_frac": round(delta, 4) if delta is not None else None,
            "victim_sheds": res["victim_sheds"],
            "flood_offered": res["flood_offered"],
            "flood_shed": res["flood_shed"],
            "target_delta_frac": 0.10,
            # The guarantee: victim p99 moved <= 10%, no victim sheds,
            # and the flood really was over its share (mostly 429s).
            "meets_target": bool(
                delta is not None and delta <= 0.10
                and res["victim_sheds"] == 0
                and res["flood_shed"] > res["flood_offered"] * 0.5
            ),
        }
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def run_disagg_phase() -> dict:
    """Disaggregated P/D pools vs the fused fleet (docs/disagg.md): the
    SAME four fake engines under the chip queueing model
    (--chip-ms-per-ktok: prefill slices and decode slices serialize per
    engine — the head-of-line interference disagg removes), driven at the
    same offered qps through the real router twice — once fused, once as
    2 prefill + 2 decode pools with the streamed KV handoff over a real
    kvserver. Headline: p99 TTFT paired delta at the high-qps point while
    holding tokens/s/chip, plus the overlap fraction and the fallback
    count (must be zero on a healthy run)."""
    import aiohttp

    import socket

    model = "fake/model"
    env = dict(os.environ, PYTHONPATH=REPO)
    # Env-tunable so --tiny (and CI's bench-smoke) can shrink the load
    # without forking the protocol.
    n_requests = int(os.environ.get("PST_BENCH_DISAGG_REQUESTS", "150"))
    offered_qps = float(os.environ.get("PST_BENCH_DISAGG_QPS", "24.0"))
    # Mixed workload: heavy prefills (the head-of-line blockers) and
    # light TTFT-sensitive requests, Poisson arrivals — the tail of the
    # light class is where fused interference shows.
    heavy_prompt = "payload words " * 250    # ~500 fake tokens
    light_prompt = "payload words " * 50     # ~100 fake tokens
    heavy_tokens, light_tokens = 64, 8

    def free_port() -> int:
        # Ephemeral allocation instead of the fixed-port + ensure_port_free
        # pattern: this phase runs two back-to-back stacks and the first
        # one's TIME_WAIT sockets would trip the fixed check; a port the
        # kernel just handed out cannot hide a stale server.
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def measure(tag: str, pools, kv_url, mid_load=None) -> dict:
        ports = [free_port() for _ in range(5)]
        rport = ports[-1]
        procs = []
        try:
            for i, p in enumerate(ports[:-1]):
                lg = f"/tmp/pst_disagg_engine_{tag}_{p}.log"
                procs.append(subprocess.Popen(
                    [sys.executable, "-m",
                     "production_stack_tpu.testing.fake_engine",
                     "--port", str(p), "--model", model,
                     "--speed", "200", "--name", f"{tag}-{i}",
                     "--chip-ms-per-ktok", "60",
                     "--kv-url", kv_url],
                    stdout=open(lg, "w"), stderr=subprocess.STDOUT,
                    cwd=REPO, env=env,
                ))
            for p in ports[:-1]:
                if not wait_http(f"http://127.0.0.1:{p}/health", 60):
                    raise RuntimeError(f"disagg fake engine :{p} not healthy")
            rlog = f"/tmp/pst_disagg_router_{tag}.log"
            args = [
                sys.executable, "-m", "production_stack_tpu.router.app",
                "--port", str(rport),
                "--service-discovery", "static",
                "--static-backends",
                ",".join(f"http://127.0.0.1:{p}" for p in ports[:-1]),
                "--static-models", ",".join([model] * len(ports[:-1])),
                "--routing-logic", "roundrobin",
                "--engine-stats-interval", "1",
            ]
            if pools:
                args += ["--static-pools", ",".join(pools)]
            procs.append(subprocess.Popen(
                args, stdout=open(rlog, "w"), stderr=subprocess.STDOUT,
                cwd=REPO, env=env,
            ))
            if not wait_http(f"http://127.0.0.1:{rport}/health", 60,
                             log_path=rlog):
                raise RuntimeError(f"disagg router ({tag}) not healthy")
            base = f"http://127.0.0.1:{rport}"

            async def one(session, i: int) -> dict:
                heavy = i % 2 == 0
                t0 = time.monotonic()
                ttft = None
                tokens = 0
                async with session.post(
                    f"{base}/v1/completions",
                    json={"model": model,
                          "prompt": heavy_prompt if heavy else light_prompt,
                          "max_tokens": (heavy_tokens if heavy
                                         else light_tokens),
                          "stream": True},
                ) as resp:
                    ok = resp.status == 200
                    async for chunk, _ in resp.content.iter_chunks():
                        if chunk.strip():
                            if ttft is None:
                                ttft = time.monotonic() - t0
                            tokens += chunk.count(b'"text"')
                return {"ok": ok, "ttft": ttft,
                        "wall": time.monotonic() - t0, "tokens": tokens}

            async def drive() -> list:
                # Poisson arrivals with a FIXED seed: both modes see the
                # same arrival sequence (paired design).
                import random as _random

                rng = _random.Random(17)
                gaps = [rng.expovariate(offered_qps)
                        for _ in range(n_requests)]
                async with aiohttp.ClientSession() as session:
                    tasks = []
                    for i in range(n_requests):
                        if mid_load is not None and i == n_requests // 3:
                            mid_load()  # e.g. SIGKILL a kvserver shard
                        tasks.append(asyncio.create_task(one(session, i)))
                        await asyncio.sleep(gaps[i])
                    return await asyncio.gather(*tasks)

            t_start = time.monotonic()
            results = asyncio.run(drive())
            wall = time.monotonic() - t_start
            with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
                metrics = r.read().decode()
            # Engine-side fused fallbacks (prefetch timed out → local
            # recompute) never reach the router's counter: a "healthy"
            # run gate blind to them would pass with zero KV actually
            # transferred.
            engine_fallbacks = 0
            published = prefetched = 0
            for p in ports[:-1]:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{p}/debug/state", timeout=5
                ) as r:
                    st = json.loads(r.read())
                engine_fallbacks += int(st.get("kv_transfer_fallbacks", 0))
                published += int(st.get("kv_published_blocks", 0))
                prefetched += int(st.get("kv_prefetched_blocks", 0))
            # Tail forensics while this leg's stack is still alive: an
            # unexplained e2e tail here harvests live evidence (the
            # engines are torn down in the finally below).
            ttfts = sorted(r["ttft"] for r in results
                           if r["ok"] and r["ttft"] is not None)
            if ttfts:
                q = lambda f: ttfts[min(int(len(ttfts) * f),  # noqa: E731
                                        len(ttfts) - 1)]
                bundle = forensics_collector().maybe_collect(
                    "disagg", tag, q(0.5) * 1e3, q(0.99) * 1e3,
                    engines=[f"http://127.0.0.1:{p}" for p in ports[:-1]],
                    router=base,
                    detail={"offered_qps": offered_qps,
                            "n_requests": n_requests},
                )
                if bundle:
                    log(f"forensics: disagg/{tag} tail bar crossed "
                        f"-> {bundle}")
            return {"results": results, "wall": wall, "metrics": metrics,
                    "engine_fallbacks": engine_fallbacks,
                    "published": published, "prefetched": prefetched}
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.send_signal(signal.SIGTERM)
            for proc in procs:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()

    def mval(text: str, name: str, label: str = "") -> float:
        for line in text.splitlines():
            if line.startswith(name) and (not label or label in line):
                return float(line.rsplit(" ", 1)[1])
        return 0.0

    def pct(samples, q):
        ordered = sorted(samples)
        return ordered[min(int(len(ordered) * q), len(ordered) - 1)]

    kv_port = free_port()
    kv_proc = subprocess.Popen(
        [sys.executable, "-m", "production_stack_tpu.kvserver.server",
         "--host", "127.0.0.1", "--port", str(kv_port)],
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        cwd=REPO, env=env,
    )
    try:
        kv_url = f"http://127.0.0.1:{kv_port}"
        if not wait_http(f"{kv_url}/health", 30):
            raise RuntimeError("disagg kvserver not healthy")
        fused = measure("fused", None, kv_url)
        disagg = measure(
            "disagg", ["prefill", "prefill", "decode", "decode"], kv_url,
        )
    finally:
        if kv_proc.poll() is None:
            kv_proc.send_signal(signal.SIGTERM)
        try:
            kv_proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            kv_proc.kill()

    # kvserver_kill variant (docs/kvserver.md degradation matrix): the
    # same P/D pools over a 3-shard replicated ring (R=2); one shard is
    # SIGKILLed a third of the way through the offered load. The guarantee
    # under test: zero fused fallbacks and a prefetch hit rate within 5%
    # of the healthy-ring baseline.
    shard_ports = [free_port() for _ in range(3)]
    shard_urls = [f"http://127.0.0.1:{p}" for p in shard_ports]
    shard_procs = [
        subprocess.Popen(
            [sys.executable, "-m", "production_stack_tpu.kvserver.server",
             "--host", "127.0.0.1", "--port", str(p),
             "--peers", ",".join(shard_urls),
             "--self-url", shard_urls[i],
             "--replication", "2", "--sweep-interval-s", "1"],
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
            cwd=REPO, env=env,
        )
        for i, p in enumerate(shard_ports)
    ]
    try:
        for u in shard_urls:
            if not wait_http(f"{u}/health", 30):
                raise RuntimeError("disagg kvserver shard not healthy")
        chaos = measure(
            "shardkill", ["prefill", "prefill", "decode", "decode"],
            ",".join(shard_urls), mid_load=shard_procs[1].kill,
        )
    finally:
        for proc in shard_procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in shard_procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def summarize(run) -> dict:
        oks = [r for r in run["results"] if r["ok"] and r["ttft"] is not None]
        toks = sum(r["tokens"] for r in run["results"])
        return {
            "ok": len(oks),
            "p50": pct([r["ttft"] for r in oks], 0.5) if oks else None,
            "p99": pct([r["ttft"] for r in oks], 0.99) if oks else None,
            "tok_s_chip": toks / run["wall"] / 4.0,
        }

    f, d = summarize(fused), summarize(disagg)
    requests_ok = f["ok"] == n_requests and d["ok"] == n_requests
    overlap_sum = mval(disagg["metrics"], "pst_disagg_overlap_seconds_sum")
    transfer_sum = mval(disagg["metrics"], "pst_disagg_transfer_seconds_sum")
    fallbacks = sum(
        mval(disagg["metrics"], "pst_disagg_fallback_total",
             f'reason="{reason}"')
        for reason in ("prefill_error", "no_decode_backend", "deadline")
    ) + disagg.get("engine_fallbacks", 0)
    tok_delta = (
        (d["tok_s_chip"] - f["tok_s_chip"]) / f["tok_s_chip"]
        if f["tok_s_chip"] else None
    )

    def hit_rate(run) -> float:
        return run["prefetched"] / run["published"] if run["published"] else 0.0

    chaos_ok = sum(1 for r in chaos["results"] if r["ok"])
    chaos_fallbacks = int(
        sum(
            mval(chaos["metrics"], "pst_disagg_fallback_total",
                 f'reason="{reason}"')
            for reason in ("prefill_error", "no_decode_backend", "deadline")
        ) + chaos.get("engine_fallbacks", 0)
    )
    hit_rate_delta = round(hit_rate(chaos) - hit_rate(disagg), 4)
    kvserver_kill = {
        "requests_ok": chaos_ok == n_requests,
        "fallbacks": chaos_fallbacks,
        "hit_rate_healthy": round(hit_rate(disagg), 4),
        "hit_rate_shard_killed": round(hit_rate(chaos), 4),
        "hit_rate_delta": hit_rate_delta,
        # One dead shard of three at R=2: every request still serves,
        # nothing degrades to the fused path, and the transfer hit rate
        # holds within 5 points of the healthy ring.
        "meets_target": bool(
            chaos_ok == n_requests
            and chaos_fallbacks == 0
            and abs(hit_rate_delta) <= 0.05
        ),
    }
    return {
        "offered_qps": offered_qps,
        "requests": n_requests,
        "requests_ok": requests_ok,
        "p50_ttft_fused_ms": round(f["p50"] * 1e3, 1) if f["p50"] else None,
        "p99_ttft_fused_ms": round(f["p99"] * 1e3, 1) if f["p99"] else None,
        "p50_ttft_disagg_ms": round(d["p50"] * 1e3, 1) if d["p50"] else None,
        "p99_ttft_disagg_ms": round(d["p99"] * 1e3, 1) if d["p99"] else None,
        "tok_s_chip_fused": round(f["tok_s_chip"], 2),
        "tok_s_chip_disagg": round(d["tok_s_chip"], 2),
        "tok_s_chip_delta_frac": (
            round(tok_delta, 4) if tok_delta is not None else None
        ),
        "overlap_fraction": (
            round(overlap_sum / transfer_sum, 4) if transfer_sum else 0.0
        ),
        "fallbacks": int(fallbacks),
        "kvserver_kill": kvserver_kill,
        "target_tok_delta_frac": 0.05,
        # The guarantee: P/D pools beat the fused fleet on p99 TTFT at
        # this qps while holding tokens/s/chip within 5%, with every
        # request served and zero fused-path fallbacks.
        "meets_target": bool(
            requests_ok
            and f["p99"] is not None and d["p99"] is not None
            and d["p99"] < f["p99"]
            and tok_delta is not None and abs(tok_delta) <= 0.05
            and fallbacks == 0
        ),
    }


def probe_backend() -> str:
    proc = subprocess.run(
        [sys.executable, "-c", "import jax; print(jax.default_backend())"],
        stdout=subprocess.PIPE, text=True, env=child_env(), timeout=120,
    )
    return proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "cpu"


# The watchdog thread and the main thread both emit; without the lock a
# T−30s force-emit could interleave with a phase emit and the "last
# stdout line is parseable JSON" contract would be the casualty.
_EMIT_LOCK = threading.Lock()


def emit(out: dict) -> None:
    """Emit the (cumulative) result: one JSON line on stdout per phase —
    the LAST stdout line is always a complete, parseable JSON object, so
    a harness that kills this process mid-run still parses every phase
    that finished — plus an atomic copy at $PST_BENCH_OUT when set."""
    with _EMIT_LOCK:
        print(json.dumps(out), flush=True)
        path = os.environ.get("PST_BENCH_OUT")
        if not path:
            return
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(out, f)
            os.replace(tmp, path)
        except OSError as e:
            log(f"could not write {path}: {e}")


_FORENSICS = None


def forensics_collector():
    """Lazy singleton: every phase shares one collector so its bundle
    list (and the evidence dir) is run-scoped, not phase-scoped."""
    global _FORENSICS
    if _FORENSICS is None:
        from production_stack_tpu.obs.forensics import (
            ForensicsCollector, evidence_dir_for,
        )

        _FORENSICS = ForensicsCollector(
            evidence_dir_for(os.environ.get("PST_BENCH_OUT"))
        )
    return _FORENSICS


def engine_snapshot_dir() -> str:
    """Where the engine child persists flight snapshots (the post-mortem
    forensics path): inside the run's evidence dir so bundles and their
    raw snapshots travel together."""
    return os.environ.get(
        "PST_BENCH_FLIGHT_SNAPSHOT_DIR",
        os.path.join(forensics_collector().evidence_dir, "engine_flight"),
    )


def collect_engine_tail_evidence(engine_res: dict) -> list:
    """Post-mortem forensics over the engine phase's sweep points: the
    child is gone by the time its JSON is parsed, so a tail-outlier point
    (r05's 120 s p99 at qps 0.5) is matched against whatever snapshots
    the engine persisted to --flight-snapshot-dir before dying."""
    from production_stack_tpu.obs.forensics import crosses_tail_bar

    collector = forensics_collector()
    snap_dir = engine_snapshot_dir()
    bundles = []
    sweeps = [(engine_res.get("model") or "flagship",
               engine_res.get("sweep") or [])]
    for key in ("concurrency_8users", "llama_1b"):
        sub = engine_res.get(key)
        if isinstance(sub, dict):
            sweeps.append((key, sub.get("sweep") or []))
    for tag, sweep in sweeps:
        for p in sweep:
            if not isinstance(p, dict):
                continue
            trigger = crosses_tail_bar(
                p.get("p50_ttft_ms"), p.get("p99_ttft_ms")
            )
            if trigger is None:
                continue
            path = collector.collect_postmortem(
                f"engine_{tag}", f"qps{p.get('qps')}",
                snapshot_dirs=[snap_dir],
                detail={"trigger": trigger, **p},
            )
            if path:
                bundles.append(path)
                log(f"forensics: engine tail outlier ({tag} qps "
                    f"{p.get('qps')}) -> {path}")
    return bundles


def assemble(engine_res: dict, stack, fleet, tenants=None, cost=None,
             disagg=None, autoscale=None) -> dict:
    flag = engine_res.get("flagship", {})
    p50 = flag.get("p50_ttft_ms")
    return {
        "metric": "p50_ttft_warm",
        "value": p50,
        "unit": "ms",
        "vs_baseline": (
            round(TTFT_TARGET_S * 1e3 / p50, 3) if p50 else None
        ),
        "backend": engine_res.get("backend", "unknown"),
        "rpc_floor_ms": engine_res.get("rpc_floor_ms"),
        **{k: v for k, v in flag.items() if k != "p50_ttft_ms"},
        "concurrency_8users": engine_res.get("concurrency_8users"),
        "llama_1b": engine_res.get("llama_1b"),
        # Warmup story: restart_to_ready_seconds for a warm restart against
        # the persistent compile cache, and the run-level compile-pollution
        # verdict --require-warm enforces. Partial engine results may lack
        # the run-level verdict — fall back to the flagship phase's flag
        # so pollution is never hidden by a truncated run.
        "warm_restart": engine_res.get("warm_restart"),
        "compile_polluted": engine_res.get(
            "compile_polluted", flag.get("compile_polluted")
        ),
        "stack": stack,
        "fleet": fleet,
        "tenants": tenants,
        "cost": cost,
        "disagg": disagg,
        "autoscale": autoscale,
    }


def parse_time_budget(argv) -> float:
    """--time-budget SECONDS (or PST_BENCH_TIME_BUDGET): total wall this
    run may spend, carved into per-phase walls. 0 = unbudgeted."""
    for i, a in enumerate(argv):
        if a == "--time-budget" and i + 1 < len(argv):
            return float(argv[i + 1])
        if a.startswith("--time-budget="):
            return float(a.split("=", 1)[1])
    return float(os.environ.get("PST_BENCH_TIME_BUDGET", "0") or 0)


# Relative phase weights for budget carving (engine dominates: it pays
# the XLA warmup; the stack-side phases are fake-engine-cheap and the
# cost audit runs the tiny model).
_PHASE_WEIGHTS = {"engine": 6.0, "stack": 1.5, "fleet": 1.5, "tenants": 1.0,
                  "disagg": 1.0, "autoscale": 1.0, "cost": 0.5}


def finalize(state: dict, extra: dict = None) -> dict:
    """Assemble the cumulative result PLUS the verdicts block — the
    shape every terminal emit (normal, watchdog, interrupted) shares, so
    the driver's last-line parse always finds the same contract."""
    out = assemble(state["engine"], state["stack"], state["fleet"],
                   state["tenants"], state["cost"], state["disagg"],
                   state.get("autoscale"))
    if _FORENSICS is not None and _FORENSICS.bundles:
        out["evidence_bundles"] = list(_FORENSICS.bundles)
    if extra:
        out.update(extra)
    if state.get("watchdog_fired"):
        out["watchdog_fired"] = True
    try:
        from benchmarks.verdicts import evaluate_round

        out["verdicts"] = evaluate_round(out)
    except Exception as e:  # noqa: BLE001 — verdicts must not kill the emit
        out["verdicts"] = {"ok": False, "error": f"verdicts failed: {e}"}
    return out


def start_watchdog(budget: TimeBudget, state: dict,
                   lead: float = WATCHDOG_LEAD_S) -> threading.Event:
    """Arm the T−lead force-emit (the r05 hole: rc 124 with nothing on
    stdout). If the run is still going ``lead`` seconds before the
    budget's wall, the watchdog emits the partial result under the emit
    lock and SIGTERMs the main thread so it unwinds through the phase
    cleanups to the final emit. Returns the stop event the happy path
    sets before its own terminal emit."""
    stop = threading.Event()

    def _fire() -> None:
        delay = max(budget.remaining() - lead, 0.5)
        if stop.wait(delay):
            return
        state["watchdog_fired"] = True
        log(f"watchdog: T-{lead:.0f}s before the wall — force-emitting "
            "the partial result and interrupting the run")
        emit(finalize(state, {"partial": True}))
        os.kill(os.getpid(), signal.SIGTERM)

    threading.Thread(target=_fire, daemon=True,
                     name="bench-watchdog").start()
    return stop


def main() -> None:
    # --all is accepted for driver ergonomics and is the default anyway:
    # every phase (engine, stack, fleet, tenants, cost) runs unless its
    # PST_BENCH_SKIP_* env is set.
    # --require-warm (or PST_BENCH_REQUIRE_WARM=1): the engine phase exits
    # nonzero when any measured sweep point absorbs a cold XLA compile, and
    # this process mirrors the verdict after emitting the full result.
    require_warm = "--require-warm" in sys.argv[1:] or (
        os.environ.get("PST_BENCH_REQUIRE_WARM") == "1"
    )
    if require_warm:
        os.environ["PST_BENCH_REQUIRE_WARM"] = "1"
    # --tiny (or PST_BENCH_TINY=1): the CPU smoke profile CI's
    # bench-smoke job runs — small pair counts, light disagg load, a
    # 240 s budget. Only missing knobs are defaulted, so a caller can
    # still pin any of them.
    tiny = "--tiny" in sys.argv[1:] or os.environ.get("PST_BENCH_TINY") == "1"
    if tiny:
        os.environ["PST_BENCH_TINY"] = "1"
        os.environ.setdefault("PST_BENCH_CPU", "1")
        os.environ.setdefault("PST_BENCH_PAIRS", "40")
        os.environ.setdefault("PST_BENCH_PAIRS_R2", "24")
        os.environ.setdefault("PST_BENCH_DISAGG_REQUESTS", "40")
        os.environ.setdefault("PST_BENCH_DISAGG_QPS", "12.0")
    total = parse_time_budget(sys.argv[1:])
    if total <= 0:
        # Never run unbudgeted: r05's rc:124 was an unbudgeted run hitting
        # the driver's external wall mid-bring-up with nothing flushed.
        total = TINY_TIME_BUDGET_S if tiny else DEFAULT_TIME_BUDGET_S
        log(f"no --time-budget given; defaulting to {total:.0f}s "
            f"({'tiny' if tiny else 'full'} profile)")
    budget = TimeBudget(total)
    install_term_trap()
    interrupted = False
    weights_left = sum(_PHASE_WEIGHTS.values())
    state = {"engine": {"backend": "unknown"}, "stack": None, "fleet": None,
             "tenants": None, "cost": None, "disagg": None, "autoscale": None}
    watchdog_stop = start_watchdog(budget, state)

    engine_res = {"backend": "unknown"}
    try:
        if os.environ.get("PST_BENCH_SKIP_ENGINE") == "1":  # stack-only debug
            engine_res = {"backend": probe_backend()}
        else:
            if budget.enabled:
                # The engine child enforces its own wall (and flushes its
                # partial) via the existing timeout env + its budget env.
                wall = budget.phase_wall(
                    _PHASE_WEIGHTS["engine"], weights_left
                )
                os.environ["PST_BENCH_ENGINE_TIMEOUT"] = str(int(wall) + 60)
                os.environ["PST_BENCH_ENGINE_BUDGET"] = str(int(wall))
            engine_res = run_engine_phase()
    except BenchInterrupted as e:
        log(f"engine phase interrupted ({e}); flushing partial result")
        partial = read_partial(os.environ.get(
            "PST_BENCH_ENGINE_OUT", "/tmp/pst_bench_engine_partial.json"
        ))
        engine_res = partial or engine_res
        engine_res["partial"] = True
        engine_res["error"] = f"interrupted: {e}"
        interrupted = True
    weights_left -= _PHASE_WEIGHTS["engine"]
    backend = engine_res.get("backend", "unknown")
    on_tpu = backend == "tpu"
    state["engine"] = engine_res
    emit(assemble(engine_res, None, None))
    try:
        # Post-mortem forensics: tail-outlier sweep points matched to the
        # flight snapshots the (now dead) engine child persisted.
        collect_engine_tail_evidence(engine_res)
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        log(f"forensics: engine tail scan failed: {e}")

    def run_phase(key, fn):
        """One budget-walled stack-side phase: skipped outright when the
        budget is gone, marked partial when the wall (or a SIGTERM) cut
        it short — the final JSON always says what happened."""
        nonlocal interrupted, weights_left
        weight = _PHASE_WEIGHTS[key]
        try:
            if interrupted or budget.exhausted():
                # Say WHICH wall cut the run: an external SIGTERM is not
                # a misconfigured budget.
                return {"partial": True,
                        "skipped": ("interrupted" if interrupted
                                    else "time budget exhausted")}
            if budget.enabled:
                phase_alarm(budget.phase_wall(weight, weights_left))
            try:
                return fn()
            finally:
                phase_alarm(0.0)
        except BenchInterrupted as e:
            log(f"{key} phase interrupted ({e})")
            interrupted = str(e).startswith("signal 15")
            return {"partial": True, "error": f"interrupted: {e}"}
        except Exception as e:  # noqa: BLE001 — phase numbers are additive
            log(f"{key} phase failed: {e}")
            return {"error": str(e)}
        finally:
            weights_left -= weight

    stack = None
    if os.environ.get("PST_BENCH_SKIP_STACK") != "1":
        stack = run_phase("stack", lambda: run_stack_phase(on_tpu))
        state["stack"] = stack
        emit(assemble(engine_res, stack, None))

    fleet = None
    if os.environ.get("PST_BENCH_SKIP_FLEET") != "1":
        fleet = run_phase("fleet", run_fleet_phase)
        state["fleet"] = fleet
        emit(assemble(engine_res, stack, fleet))

    tenants = None
    if os.environ.get("PST_BENCH_SKIP_TENANTS") != "1":
        tenants = run_phase("tenants", run_tenant_phase)
        state["tenants"] = tenants
        emit(assemble(engine_res, stack, fleet, tenants))

    disagg = None
    if os.environ.get("PST_BENCH_SKIP_DISAGG") != "1":
        disagg = run_phase("disagg", run_disagg_phase)
        state["disagg"] = disagg
        emit(assemble(engine_res, stack, fleet, tenants, disagg=disagg))

    autoscale = None
    if os.environ.get("PST_BENCH_SKIP_AUTOSCALE") != "1":
        autoscale = run_phase("autoscale", run_autoscale_phase)
        state["autoscale"] = autoscale
        emit(assemble(engine_res, stack, fleet, tenants, disagg=disagg,
                      autoscale=autoscale))

    cost = None
    if os.environ.get("PST_BENCH_SKIP_COST") != "1":
        cost = run_phase("cost", run_cost_phase)
        state["cost"] = cost

    watchdog_stop.set()
    emit(finalize(state, {"interrupted": True} if interrupted else None))
    # Same fallback as assemble(): a truncated engine phase may carry only
    # per-phase pollution flags, never the run-level verdict — the exit
    # gate must not be laxer than the emitted JSON.
    polluted = engine_res.get("compile_polluted") or any(
        isinstance(v, dict) and v.get("compile_polluted")
        for v in engine_res.values()
    )
    if require_warm and polluted:
        log("--require-warm: measured sweep points were compile-polluted; "
            "exiting nonzero (full result emitted above)")
        sys.exit(3)


if __name__ == "__main__":
    main()
