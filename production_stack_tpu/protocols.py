"""OpenAI-compatible protocol models shared by engine server and router.

Parity surface: the reference router's ``src/vllm_router/protocols.py:11-56``
(ModelCard/ModelList/ErrorResponse) plus the request/response bodies the
vLLM OpenAI server speaks (the engine here implements them natively).
Unknown extra fields are accepted and logged, as in the reference.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field, model_validator

from .logging_utils import init_logger

logger = init_logger(__name__)


def random_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


class _Permissive(BaseModel):
    """Base model that tolerates (and logs) unknown fields."""

    model_config = ConfigDict(extra="allow", protected_namespaces=())

    @model_validator(mode="after")
    def _log_extra(self):
        if self.model_extra:
            logger.debug(
                "%s received extra fields: %s",
                type(self).__name__,
                sorted(self.model_extra),
            )
        return self


# ----------------------------------------------------------------------------
# Models listing
# ----------------------------------------------------------------------------


class ModelCard(_Permissive):
    id: str
    object: str = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "production-stack-tpu"
    root: Optional[str] = None
    parent: Optional[str] = None


class ModelList(_Permissive):
    object: str = "list"
    data: List[ModelCard] = Field(default_factory=list)


class ErrorResponse(_Permissive):
    object: str = "error"
    message: str
    type: str = "invalid_request_error"
    code: int = 400
    param: Optional[str] = None


# ----------------------------------------------------------------------------
# Chat / completion requests
# ----------------------------------------------------------------------------


class ChatMessage(_Permissive):
    role: Literal["system", "user", "assistant", "tool"] = "user"
    content: Union[str, List[Dict[str, Any]], None] = None
    name: Optional[str] = None

    def text(self) -> str:
        if isinstance(self.content, str):
            return self.content
        if isinstance(self.content, list):
            return "".join(
                part.get("text", "")
                for part in self.content
                if isinstance(part, dict) and part.get("type", "text") == "text"
            )
        return ""


class SamplingFields(_Permissive):
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = -1
    min_p: float = 0.0
    n: int = 1
    stop: Union[str, List[str], None] = None
    stop_token_ids: Optional[List[int]] = None
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    seed: Optional[int] = None
    logprobs: Union[bool, int, None] = None
    top_logprobs: Optional[int] = None
    # OpenAI logit_bias: {"<token_id>": bias in [-100, 100]}.
    logit_bias: Optional[Dict[str, float]] = None
    # Guided decoding (vLLM extra-body extension): constrain the output to
    # be exactly one of these strings.
    guided_choice: Optional[List[str]] = None
    ignore_eos: bool = False
    stream: bool = False
    stream_options: Optional[Dict[str, Any]] = None
    user: Optional[str] = None


class CompletionRequest(SamplingFields):
    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]] = ""
    echo: bool = False
    suffix: Optional[str] = None
    # best_of > n: sample best_of candidates, return the n with the highest
    # mean token logprob (OpenAI/vLLM semantics; non-streaming only).
    best_of: Optional[int] = None


class ChatCompletionRequest(SamplingFields):
    model: str
    messages: List[ChatMessage] = Field(default_factory=list)
    tools: Optional[List[Dict[str, Any]]] = None
    tool_choice: Union[str, Dict[str, Any], None] = None
    response_format: Optional[Dict[str, Any]] = None


class EmbeddingRequest(_Permissive):
    model: str
    input: Union[str, List[str], List[int], List[List[int]]] = ""
    encoding_format: str = "float"
    dimensions: Optional[int] = None


class TokenizeRequest(_Permissive):
    model: Optional[str] = None
    prompt: Optional[str] = None
    messages: Optional[List[ChatMessage]] = None
    add_special_tokens: bool = True


class DetokenizeRequest(_Permissive):
    model: Optional[str] = None
    tokens: List[int] = Field(default_factory=list)


class RerankRequest(_Permissive):
    model: Optional[str] = None
    query: str = ""
    documents: List[str] = Field(default_factory=list)
    top_n: Optional[int] = None


class ScoreRequest(_Permissive):
    model: Optional[str] = None
    text_1: Union[str, List[str]] = ""
    text_2: Union[str, List[str]] = ""


# ----------------------------------------------------------------------------
# Responses
# ----------------------------------------------------------------------------


class UsageInfo(_Permissive):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class CompletionChoice(_Permissive):
    index: int = 0
    text: str = ""
    logprobs: Optional[Dict[str, Any]] = None
    finish_reason: Optional[str] = None


class CompletionResponse(_Permissive):
    id: str = Field(default_factory=lambda: random_id("cmpl"))
    object: str = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[CompletionChoice] = Field(default_factory=list)
    usage: UsageInfo = Field(default_factory=UsageInfo)


class ChatCompletionMessage(_Permissive):
    role: str = "assistant"
    content: Optional[str] = None


class ChatChoice(_Permissive):
    index: int = 0
    message: ChatCompletionMessage = Field(default_factory=ChatCompletionMessage)
    logprobs: Optional[Dict[str, Any]] = None
    finish_reason: Optional[str] = None


class ChatCompletionResponse(_Permissive):
    id: str = Field(default_factory=lambda: random_id("chatcmpl"))
    object: str = "chat.completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[ChatChoice] = Field(default_factory=list)
    usage: UsageInfo = Field(default_factory=UsageInfo)


class DeltaMessage(_Permissive):
    role: Optional[str] = None
    content: Optional[str] = None


class ChatStreamChoice(_Permissive):
    index: int = 0
    delta: DeltaMessage = Field(default_factory=DeltaMessage)
    finish_reason: Optional[str] = None


class ChatCompletionChunk(_Permissive):
    id: str = ""
    object: str = "chat.completion.chunk"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[ChatStreamChoice] = Field(default_factory=list)
    usage: Optional[UsageInfo] = None


class EmbeddingData(_Permissive):
    object: str = "embedding"
    index: int = 0
    embedding: List[float] = Field(default_factory=list)


class EmbeddingResponse(_Permissive):
    object: str = "list"
    data: List[EmbeddingData] = Field(default_factory=list)
    model: str = ""
    usage: UsageInfo = Field(default_factory=UsageInfo)
