"""Scrape engine /metrics endpoints and keep a live per-engine snapshot.

Capability parity with the reference's ``src/vllm_router/stats/engine_stats.py``
(EngineStats.from_vllm_scrape :42-85, EngineStatsScraper :88-209). The
scraper is an asyncio task (not a daemon thread) and parses the same
``vllm:``-prefixed gauge names our TPU engine exports, so reference
dashboards keep working unchanged.

Ownership (router HA): the scraper is a plain class — no ``SingletonMeta``
— created by the app factory and *injected* per app (``create_app`` binds
it into request context via middleware), the same de-singletonization
``RequestStatsMonitor`` got in the HA PR. Two router apps in one process
(the multi-replica tests) each scrape into their OWN snapshot — zero
engine-stats bleed — while every existing ``get_engine_stats_scraper()``
call site keeps working via the per-request context binding with an
app-scope fallback (``router.appscope``; the module-default global died
with the app-scope pstlint check).
"""

# pstlint: disable-file=hop-contract(metrics scrapes are control-plane pulls on their own timer; no originating client request exists to propagate headers from)
from __future__ import annotations

import asyncio
import contextvars
from dataclasses import dataclass
from typing import Dict, Optional

import aiohttp
from prometheus_client.parser import text_string_to_metric_families

from ...logging_utils import init_logger
from ..service_discovery import get_service_discovery

logger = init_logger(__name__)

_METRIC_FIELDS = {
    "vllm:num_requests_running": "num_running_requests",
    "vllm:num_requests_waiting": "num_queuing_requests",
    "vllm:gpu_prefix_cache_hit_rate": "gpu_prefix_cache_hit_rate",
    "vllm:gpu_prefix_cache_hits_total": "gpu_prefix_cache_hits_total",
    "vllm:gpu_prefix_cache_queries_total": "gpu_prefix_cache_queries_total",
    "vllm:gpu_cache_usage_perc": "gpu_cache_usage_perc",
    # Engine telemetry (docs/observability.md "Engine telemetry").
    "pst_engine_compile_total": "engine_compiles_total",
    "pst_engine_mfu": "engine_mfu",
    "pst_engine_kv_page_occupancy": "engine_kv_page_occupancy",
    "pst_engine_kv_page_high_watermark": "engine_kv_page_high_watermark",
    "pst_engine_warmup_coverage": "engine_warmup_coverage",
    # Remote-KV health (docs/kvserver.md): the disagg decode scorer
    # penalizes engines whose remote tier is degrading (fused-recompute
    # fallbacks, corrupt replica copies detected on read).
    "pst:kv_transfer_fallbacks_total": "kv_transfer_fallbacks_total",
    "pst_kv_integrity_failures_total": "kv_integrity_failures_total",
}

# Histogram whose p50 the scraper estimates from bucket counts (summed
# over label sets): the decode-loop host gap, so /engines and
# /debug/fleet surface the overlap-pipeline health without operators
# scraping engines directly.
_HOST_GAP_BUCKET = "pst_engine_host_gap_seconds_bucket"


def _bucket_quantile(buckets, q: float) -> float:
    """Estimate a quantile from cumulative ``{le: count}`` samples: the
    smallest upper bound covering q of the observations (the classic
    histogram_quantile upper-bound estimate, without interpolation —
    good enough for a health readout)."""
    if not buckets:
        return 0.0
    finite = sorted(
        (le, c) for le, c in buckets.items() if le != float("inf")
    )
    total = max(buckets.values())
    if total <= 0:
        return 0.0
    target = q * total
    for le, count in finite:
        if count >= target:
            return le
    return finite[-1][0] if finite else 0.0

# Labeled counters summed over their label sets (pst_engine_compile_total
# has one sample per {kind, shape_bucket}); everything else is a single
# sample and the last value wins.
_SUMMED_FIELDS = {
    "engine_compiles_total",
    # One sample per {source} (prefetch / match_prefix / restore).
    "kv_integrity_failures_total",
}


@dataclass
class EngineStats:
    num_running_requests: int = 0
    num_queuing_requests: int = 0
    gpu_prefix_cache_hit_rate: float = 0.0
    gpu_prefix_cache_hits_total: int = 0
    gpu_prefix_cache_queries_total: int = 0
    gpu_cache_usage_perc: float = 0.0
    engine_compiles_total: int = 0
    engine_mfu: float = 0.0
    engine_kv_page_occupancy: float = 0.0
    engine_kv_page_high_watermark: float = 0.0
    engine_warmup_coverage: float = 0.0
    # Remote-KV tier health (docs/kvserver.md).
    kv_transfer_fallbacks_total: int = 0
    kv_integrity_failures_total: int = 0
    # Estimated from the pst_engine_host_gap_seconds bucket counts.
    engine_host_gap_p50: float = 0.0

    @staticmethod
    def from_scrape(text: str) -> "EngineStats":
        """Parse an engine's ``/metrics`` body into a snapshot.

        NEVER raises: a partially-written scrape (engine restarting
        mid-response) or a malformed line must degrade to whatever parsed
        before the damage, not kill the scrape sweep — a fleet-wide stats
        blackout because one engine emitted garbage would be worse than
        the garbage.
        """
        values: Dict[str, float] = {}
        host_gap_buckets: Dict[float, float] = {}
        try:
            for family in text_string_to_metric_families(text):
                for sample in family.samples:
                    if sample.name == _HOST_GAP_BUCKET:
                        try:
                            le = float(sample.labels.get("le", "inf"))
                            host_gap_buckets[le] = (
                                host_gap_buckets.get(le, 0.0)
                                + float(sample.value)
                            )
                        except (TypeError, ValueError):
                            pass
                        continue
                    field = _METRIC_FIELDS.get(sample.name)
                    if field is None:
                        continue
                    try:
                        v = float(sample.value)
                    except (TypeError, ValueError):
                        continue
                    if field in _SUMMED_FIELDS:
                        values[field] = values.get(field, 0.0) + v
                    else:
                        values[field] = v
        except Exception as e:  # noqa: BLE001 — keep what parsed so far
            logger.debug("partial engine scrape parse: %s", e)
        if host_gap_buckets:
            values["engine_host_gap_p50"] = _bucket_quantile(
                host_gap_buckets, 0.5
            )
        stats = EngineStats()
        for field, value in values.items():
            try:
                if field.startswith("num_") or field.endswith("_total"):
                    setattr(stats, field, int(value))
                else:
                    setattr(stats, field, float(value))
            except (TypeError, ValueError, OverflowError):
                continue  # one bad sample never poisons the snapshot
        return stats

    # Back-compat alias with the reference's classmethod name.
    from_vllm_scrape = from_scrape


class EngineStatsScraper:
    def __init__(self, scrape_interval: Optional[float] = None):
        if scrape_interval is None:
            raise ValueError("EngineStatsScraper needs a scrape_interval")
        self.scrape_interval = scrape_interval
        # Written only by the scrape task (_scrape_one fills, _loop
        # drops stale urls); readers get a copy via get_engine_stats().
        # pstlint: owned-by=task:_scrape_one,_loop
        self.engine_stats: Dict[str, EngineStats] = {}
        self._task: Optional[asyncio.Task] = None

    @classmethod
    def destroy(cls) -> None:
        """Drop the current scope's scraper (test/reconfiguration hook;
        the name survives from the SingletonMeta era so existing teardown
        helpers keep working)."""
        from .. import appscope

        appscope.scoped_set(_SCOPE_KEY, None)

    async def _scrape_one(self, session: aiohttp.ClientSession, url: str) -> None:
        try:
            async with session.get(
                f"{url}/metrics", timeout=aiohttp.ClientTimeout(total=self.scrape_interval)
            ) as resp:
                resp.raise_for_status()
                text = await resp.text()
            self.engine_stats[url] = EngineStats.from_scrape(text)
        except Exception as e:  # noqa: BLE001 — engine may be booting
            logger.debug("failed scraping %s: %s", url, e)

    async def _loop(self) -> None:
        async with aiohttp.ClientSession() as session:
            while True:
                try:
                    urls = [e.url for e in get_service_discovery().get_endpoint_info()]
                    await asyncio.gather(*(self._scrape_one(session, u) for u in urls))
                    for stale in set(self.engine_stats) - set(urls):
                        del self.engine_stats[stale]
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001
                    logger.error("engine stats scrape sweep failed: %s", e)
                await asyncio.sleep(self.scrape_interval)

    async def start(self) -> None:
        if self._task is None:
            # pstlint: task-owner=_task
            self._task = asyncio.create_task(self._loop())

    def get_engine_stats(self) -> Dict[str, EngineStats]:
        return dict(self.engine_stats)

    def get_health(self) -> bool:
        return self._task is not None and not self._task.done()

    def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


# Context binding: ``create_app`` injects its own scraper for the request
# tasks it serves; the app scope (``router.appscope``) covers bootstrap
# code and background loops — there is no module-level default left to
# bleed between apps (same contract as the request-stats monitor).
_bound_scraper: contextvars.ContextVar[Optional[EngineStatsScraper]] = (
    contextvars.ContextVar("pst_engine_stats_scraper", default=None)
)
_SCOPE_KEY = "engine_stats_scraper"


def initialize_engine_stats_scraper(scrape_interval: float) -> EngineStatsScraper:
    from .. import appscope

    return appscope.scoped_set(_SCOPE_KEY, EngineStatsScraper(scrape_interval))


def bind_engine_stats_scraper(
    scraper: EngineStatsScraper,
) -> contextvars.Token:
    """Bind ``scraper`` for the current context (one request's task tree);
    returns the token for ``unbind_engine_stats_scraper``."""
    return _bound_scraper.set(scraper)


def unbind_engine_stats_scraper(token: contextvars.Token) -> None:
    _bound_scraper.reset(token)


def get_engine_stats_scraper() -> EngineStatsScraper:
    from .. import appscope

    scraper = _bound_scraper.get()
    if scraper is not None:
        return scraper
    scraper = appscope.scoped_get(_SCOPE_KEY)
    if scraper is None:
        raise ValueError("EngineStatsScraper needs a scrape_interval")
    return scraper
