"""Per-engine request-level statistics (QPS, TTFT, latency, state counts).

Capability parity with the reference's ``src/vllm_router/stats/request_stats.py``
(RequestStats :34-55, MovingAverageMonitor :58-103, RequestStatsMonitor
:106-306): requests move prefill → decode → finished, with sliding-window
averages per engine.

Ownership (router HA): the monitor is a plain class — no ``SingletonMeta``
— created by the app factory and *injected* per app (``create_app`` binds
it into request context via middleware), so multi-replica tests can run
two routers in one process without state bleed. ``get_request_stats_monitor``
resolves the context-bound monitor first and falls back to the app scope
(``router.appscope``) the enclosing app bound, which keeps every existing
call site (and single-router deployments) working unchanged — with no
module-level default left for a second app to overwrite.

Replication: ``get_request_stats`` merges live peers' snapshots from the
:class:`~..state.StateBackend` (additive counts, summed QPS) so routing
decisions see *fleet-wide* load; with the in-memory backend the merge is
the identity and behavior is byte-for-byte the single-replica one.
"""

from __future__ import annotations

import contextvars
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Tuple


@dataclass
class RequestStats:
    qps: float = 0.0
    ttft: float = -1.0
    in_prefill_requests: int = 0
    in_decoding_requests: int = 0
    finished_requests: int = 0
    uptime: float = 0.0
    avg_decoding_length: float = -1.0
    avg_latency: float = -1.0
    avg_itl: float = -1.0
    num_swapped_requests: int = 0
    failed_requests: int = 0


class MovingAverageMonitor:
    """Average of timestamped values over a sliding time window."""

    def __init__(self, window: float):
        self.window = window
        # pstlint: owned-by=task:update,_evict
        self._items: Deque[Tuple[float, float]] = deque()
        self._sum = 0.0

    def update(self, timestamp: float, value: float) -> None:
        self._items.append((timestamp, value))
        self._sum += value
        self._evict(timestamp)

    def update_no_value(self, timestamp: float) -> None:
        self.update(timestamp, 0.0)

    def _evict(self, now: float) -> None:
        while self._items and self._items[0][0] < now - self.window:
            _, v = self._items.popleft()
            self._sum -= v

    def poll(self, now: Optional[float] = None) -> None:
        self._evict(now if now is not None else time.time())

    def get_average(self) -> float:
        if not self._items:
            return -1.0
        return self._sum / len(self._items)

    def get_sum(self) -> float:
        return self._sum

    def get_count(self) -> int:
        return len(self._items)


class RequestStatsMonitor:
    """Tracks request lifecycle events reported by the proxy layer."""

    def __init__(self, sliding_window_size: Optional[float] = None):
        if sliding_window_size is None:
            raise ValueError("RequestStatsMonitor needs sliding_window_size")
        self.window = sliding_window_size
        # The proxy layer's lifecycle callbacks (on_new_request /
        # on_request_response / on_request_complete / on_request_swapped /
        # on_request_failed) are the ONLY writers of the tables below,
        # plus evict_url on engine departure and the _mon window factory.
        # get_request_stats and /metrics only read. The lock-discipline
        # pstlint check enforces the single-writer surface.
        # pstlint: owned-by=task:on_*,evict_url,_mon
        self.qps_monitors: Dict[str, MovingAverageMonitor] = {}
        # pstlint: owned-by=task:on_*,evict_url,_mon
        self.ttft_monitors: Dict[str, MovingAverageMonitor] = {}
        # pstlint: owned-by=task:on_*,evict_url,_mon
        self.latency_monitors: Dict[str, MovingAverageMonitor] = {}
        # pstlint: owned-by=task:on_*,evict_url,_mon
        self.decoding_length_monitors: Dict[str, MovingAverageMonitor] = {}
        # pstlint: owned-by=task:on_*,evict_url,_mon
        self.itl_monitors: Dict[str, MovingAverageMonitor] = {}
        # (engine_url, request_id) -> timestamps
        # pstlint: owned-by=task:on_*,evict_url
        self.request_start: Dict[Tuple[str, str], float] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.first_token_time: Dict[Tuple[str, str], float] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.last_token_time: Dict[Tuple[str, str], float] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.token_counts: Dict[Tuple[str, str], int] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.in_prefill: Dict[str, int] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.in_decoding: Dict[str, int] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.finished: Dict[str, int] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.swapped: Dict[str, int] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.failed: Dict[str, int] = {}
        self.first_query_time: Optional[float] = None

    @classmethod
    def destroy(cls) -> None:
        """Drop the current scope's monitor (test/reconfiguration hook;
        the name survives from the SingletonMeta era so existing teardown
        helpers keep working)."""
        from .. import appscope

        appscope.scoped_set(_SCOPE_KEY, None)

    def _mon(self, table: Dict[str, MovingAverageMonitor], url: str) -> MovingAverageMonitor:
        if url not in table:
            table[url] = MovingAverageMonitor(self.window)
        return table[url]

    def on_new_request(self, engine_url: str, request_id: str, timestamp: float) -> None:
        self.request_start[(engine_url, request_id)] = timestamp
        self.in_prefill[engine_url] = self.in_prefill.get(engine_url, 0) + 1
        self._mon(self.qps_monitors, engine_url).update_no_value(timestamp)
        if self.first_query_time is None:
            self.first_query_time = timestamp

    def on_request_response(self, engine_url: str, request_id: str, timestamp: float) -> None:
        """First streamed token observed → TTFT sample; request enters decode."""
        key = (engine_url, request_id)
        start = self.request_start.get(key)
        if start is None:
            return
        if key in self.first_token_time:
            # Subsequent tokens: inter-token latency sample.
            prev = self.last_token_time.get(key, timestamp)
            self._mon(self.itl_monitors, engine_url).update(timestamp, timestamp - prev)
            self.last_token_time[key] = timestamp
            self.token_counts[key] = self.token_counts.get(key, 1) + 1
            return
        self.first_token_time[key] = timestamp
        self.last_token_time[key] = timestamp
        self.token_counts[key] = 1
        self._mon(self.ttft_monitors, engine_url).update(timestamp, timestamp - start)
        self.in_prefill[engine_url] = max(0, self.in_prefill.get(engine_url, 1) - 1)
        self.in_decoding[engine_url] = self.in_decoding.get(engine_url, 0) + 1

    def on_request_complete(self, engine_url: str, request_id: str, timestamp: float) -> None:
        key = (engine_url, request_id)
        start = self.request_start.pop(key, None)
        first = self.first_token_time.pop(key, None)
        self.last_token_time.pop(key, None)
        self.token_counts.pop(key, None)
        if first is not None:
            self.in_decoding[engine_url] = max(0, self.in_decoding.get(engine_url, 1) - 1)
            self._mon(self.decoding_length_monitors, engine_url).update(
                timestamp, timestamp - first
            )
        else:
            self.in_prefill[engine_url] = max(0, self.in_prefill.get(engine_url, 1) - 1)
        if start is not None:
            self._mon(self.latency_monitors, engine_url).update(timestamp, timestamp - start)
        self.finished[engine_url] = self.finished.get(engine_url, 0) + 1

    def on_request_swapped(self, engine_url: str, request_id: str, timestamp: float) -> None:
        self.swapped[engine_url] = self.swapped.get(engine_url, 0) + 1

    def on_request_failed(self, engine_url: str, request_id: str, timestamp: float) -> None:
        """An upstream attempt against this engine failed (connect error or
        5xx, reported by the proxy's resilience layer)."""
        self.failed[engine_url] = self.failed.get(engine_url, 0) + 1

    def evict_url(self, engine_url: str) -> None:
        """Drop every per-engine aggregate for an engine that left the fleet
        for good (pod deleted / service removed) — the counterpart of the
        breaker registry's evict; without it pod churn grows these tables
        (and get_request_stats output) without bound."""
        for table in (
            self.qps_monitors, self.ttft_monitors, self.latency_monitors,
            self.decoding_length_monitors, self.itl_monitors,
            self.in_prefill, self.in_decoding, self.finished,
            self.swapped, self.failed,
        ):
            table.pop(engine_url, None)

    def _local_request_stats(self, now: float) -> Dict[str, RequestStats]:
        """This replica's own view (no peer merge) — what the gossip
        snapshot provider publishes and the merge builds on."""
        urls = (
            set(self.qps_monitors)
            | set(self.in_prefill)
            | set(self.in_decoding)
            | set(self.finished)
        )
        out: Dict[str, RequestStats] = {}
        uptime = now - self.first_query_time if self.first_query_time else 0.0
        for url in urls:
            qps_mon = self.qps_monitors.get(url)
            qps = 0.0
            if qps_mon is not None:
                qps_mon.poll(now)
                qps = qps_mon.get_count() / self.window
            def avg(table: Dict[str, MovingAverageMonitor]) -> float:
                mon = table.get(url)
                return mon.get_average() if mon is not None else -1.0

            out[url] = RequestStats(
                qps=qps,
                ttft=avg(self.ttft_monitors),
                in_prefill_requests=self.in_prefill.get(url, 0),
                in_decoding_requests=self.in_decoding.get(url, 0),
                finished_requests=self.finished.get(url, 0),
                uptime=uptime,
                avg_decoding_length=avg(self.decoding_length_monitors),
                avg_latency=avg(self.latency_monitors),
                avg_itl=avg(self.itl_monitors),
                num_swapped_requests=self.swapped.get(url, 0),
                failed_requests=self.failed.get(url, 0),
            )
        return out

    def sync_snapshot(self) -> Dict[str, dict]:
        """Compact per-engine snapshot the state backend gossips to peers
        (only what fleet-wide routing actually consumes)."""
        now = time.time()
        return {
            url: {
                "qps": rs.qps,
                "ttft": rs.ttft,
                "in_prefill": rs.in_prefill_requests,
                "in_decoding": rs.in_decoding_requests,
                "finished": rs.finished_requests,
                "failed": rs.failed_requests,
            }
            for url, rs in self._local_request_stats(now).items()
        }

    def get_request_stats(
        self, current_time: Optional[float] = None, fleet: bool = True
    ) -> Dict[str, RequestStats]:
        """Per-engine stats. With a shared state backend and ``fleet=True``
        (the default — what routing wants), live peers' snapshots merge in
        additively; ``fleet=False`` keeps the view local (the /metrics
        exposition, where each replica must export only its own traffic or
        Prometheus sums would double-count)."""
        now = current_time if current_time is not None else time.time()
        out = self._local_request_stats(now)
        if not fleet:
            return out
        from ..state import get_state_backend

        backend = get_state_backend()
        if backend is None or not backend.shared:
            return out
        for snap in backend.peer_request_stats().values():
            if not isinstance(snap, dict):
                continue
            for url, d in snap.items():
                if not isinstance(d, dict):
                    continue
                rs = out.get(url)
                if rs is None:
                    rs = RequestStats()
                    out[url] = rs
                rs.qps += float(d.get("qps") or 0.0)
                rs.in_prefill_requests += int(d.get("in_prefill") or 0)
                rs.in_decoding_requests += int(d.get("in_decoding") or 0)
                rs.finished_requests += int(d.get("finished") or 0)
                rs.failed_requests += int(d.get("failed") or 0)
                if rs.ttft < 0:
                    rs.ttft = float(d.get("ttft") if d.get("ttft") is not None else -1.0)
        return out


# Context binding: ``create_app`` injects its own monitor for the request
# tasks it serves; the app scope (``router.appscope``) covers bootstrap
# code and background loops. (A contextvar, not explicit threading, so
# the deep call graph under proxy_and_stream needs no monitor plumbing;
# the module-default global died with the app-scope pstlint check.)
_bound_monitor: contextvars.ContextVar[Optional[RequestStatsMonitor]] = (
    contextvars.ContextVar("pst_request_stats_monitor", default=None)
)
_SCOPE_KEY = "request_stats_monitor"


def initialize_request_stats_monitor(sliding_window_size: float) -> RequestStatsMonitor:
    from .. import appscope

    return appscope.scoped_set(
        _SCOPE_KEY, RequestStatsMonitor(sliding_window_size)
    )


def bind_request_stats_monitor(
    monitor: RequestStatsMonitor,
) -> contextvars.Token:
    """Bind ``monitor`` for the current context (one request's task tree);
    returns the token for ``unbind_request_stats_monitor``."""
    return _bound_monitor.set(monitor)


def unbind_request_stats_monitor(token: contextvars.Token) -> None:
    _bound_monitor.reset(token)


def get_request_stats_monitor() -> RequestStatsMonitor:
    from .. import appscope

    monitor = _bound_monitor.get()
    if monitor is not None:
        return monitor
    monitor = appscope.scoped_get(_SCOPE_KEY)
    if monitor is None:
        raise ValueError("RequestStatsMonitor needs sliding_window_size")
    return monitor
