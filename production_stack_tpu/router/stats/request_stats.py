"""Per-engine request-level statistics (QPS, TTFT, latency, state counts).

Capability parity with the reference's ``src/vllm_router/stats/request_stats.py``
(RequestStats :34-55, MovingAverageMonitor :58-103, RequestStatsMonitor
:106-306): requests move prefill → decode → finished, with sliding-window
averages per engine.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Tuple

from ...utils import SingletonMeta


@dataclass
class RequestStats:
    qps: float = 0.0
    ttft: float = -1.0
    in_prefill_requests: int = 0
    in_decoding_requests: int = 0
    finished_requests: int = 0
    uptime: float = 0.0
    avg_decoding_length: float = -1.0
    avg_latency: float = -1.0
    avg_itl: float = -1.0
    num_swapped_requests: int = 0
    failed_requests: int = 0


class MovingAverageMonitor:
    """Average of timestamped values over a sliding time window."""

    def __init__(self, window: float):
        self.window = window
        self._items: Deque[Tuple[float, float]] = deque()
        self._sum = 0.0

    def update(self, timestamp: float, value: float) -> None:
        self._items.append((timestamp, value))
        self._sum += value
        self._evict(timestamp)

    def update_no_value(self, timestamp: float) -> None:
        self.update(timestamp, 0.0)

    def _evict(self, now: float) -> None:
        while self._items and self._items[0][0] < now - self.window:
            _, v = self._items.popleft()
            self._sum -= v

    def poll(self, now: Optional[float] = None) -> None:
        self._evict(now if now is not None else time.time())

    def get_average(self) -> float:
        if not self._items:
            return -1.0
        return self._sum / len(self._items)

    def get_sum(self) -> float:
        return self._sum

    def get_count(self) -> int:
        return len(self._items)


class RequestStatsMonitor(metaclass=SingletonMeta):
    """Tracks request lifecycle events reported by the proxy layer."""

    def __init__(self, sliding_window_size: Optional[float] = None):
        if getattr(self, "_initialized", False):
            return
        if sliding_window_size is None:
            raise ValueError("RequestStatsMonitor needs sliding_window_size")
        self.window = sliding_window_size
        # The proxy layer's lifecycle callbacks (on_new_request /
        # on_request_response / on_request_complete / on_request_swapped /
        # on_request_failed) are the ONLY writers of the tables below,
        # plus evict_url on engine departure and the _mon window factory.
        # get_request_stats and /metrics only read. The lock-discipline
        # pstlint check enforces the single-writer surface.
        # pstlint: owned-by=task:on_*,evict_url,_mon
        self.qps_monitors: Dict[str, MovingAverageMonitor] = {}
        # pstlint: owned-by=task:on_*,evict_url,_mon
        self.ttft_monitors: Dict[str, MovingAverageMonitor] = {}
        # pstlint: owned-by=task:on_*,evict_url,_mon
        self.latency_monitors: Dict[str, MovingAverageMonitor] = {}
        # pstlint: owned-by=task:on_*,evict_url,_mon
        self.decoding_length_monitors: Dict[str, MovingAverageMonitor] = {}
        # pstlint: owned-by=task:on_*,evict_url,_mon
        self.itl_monitors: Dict[str, MovingAverageMonitor] = {}
        # (engine_url, request_id) -> timestamps
        # pstlint: owned-by=task:on_*,evict_url
        self.request_start: Dict[Tuple[str, str], float] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.first_token_time: Dict[Tuple[str, str], float] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.last_token_time: Dict[Tuple[str, str], float] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.token_counts: Dict[Tuple[str, str], int] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.in_prefill: Dict[str, int] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.in_decoding: Dict[str, int] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.finished: Dict[str, int] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.swapped: Dict[str, int] = {}
        # pstlint: owned-by=task:on_*,evict_url
        self.failed: Dict[str, int] = {}
        self.first_query_time: Optional[float] = None
        self._initialized = True

    def _mon(self, table: Dict[str, MovingAverageMonitor], url: str) -> MovingAverageMonitor:
        if url not in table:
            table[url] = MovingAverageMonitor(self.window)
        return table[url]

    def on_new_request(self, engine_url: str, request_id: str, timestamp: float) -> None:
        self.request_start[(engine_url, request_id)] = timestamp
        self.in_prefill[engine_url] = self.in_prefill.get(engine_url, 0) + 1
        self._mon(self.qps_monitors, engine_url).update_no_value(timestamp)
        if self.first_query_time is None:
            self.first_query_time = timestamp

    def on_request_response(self, engine_url: str, request_id: str, timestamp: float) -> None:
        """First streamed token observed → TTFT sample; request enters decode."""
        key = (engine_url, request_id)
        start = self.request_start.get(key)
        if start is None:
            return
        if key in self.first_token_time:
            # Subsequent tokens: inter-token latency sample.
            prev = self.last_token_time.get(key, timestamp)
            self._mon(self.itl_monitors, engine_url).update(timestamp, timestamp - prev)
            self.last_token_time[key] = timestamp
            self.token_counts[key] = self.token_counts.get(key, 1) + 1
            return
        self.first_token_time[key] = timestamp
        self.last_token_time[key] = timestamp
        self.token_counts[key] = 1
        self._mon(self.ttft_monitors, engine_url).update(timestamp, timestamp - start)
        self.in_prefill[engine_url] = max(0, self.in_prefill.get(engine_url, 1) - 1)
        self.in_decoding[engine_url] = self.in_decoding.get(engine_url, 0) + 1

    def on_request_complete(self, engine_url: str, request_id: str, timestamp: float) -> None:
        key = (engine_url, request_id)
        start = self.request_start.pop(key, None)
        first = self.first_token_time.pop(key, None)
        self.last_token_time.pop(key, None)
        self.token_counts.pop(key, None)
        if first is not None:
            self.in_decoding[engine_url] = max(0, self.in_decoding.get(engine_url, 1) - 1)
            self._mon(self.decoding_length_monitors, engine_url).update(
                timestamp, timestamp - first
            )
        else:
            self.in_prefill[engine_url] = max(0, self.in_prefill.get(engine_url, 1) - 1)
        if start is not None:
            self._mon(self.latency_monitors, engine_url).update(timestamp, timestamp - start)
        self.finished[engine_url] = self.finished.get(engine_url, 0) + 1

    def on_request_swapped(self, engine_url: str, request_id: str, timestamp: float) -> None:
        self.swapped[engine_url] = self.swapped.get(engine_url, 0) + 1

    def on_request_failed(self, engine_url: str, request_id: str, timestamp: float) -> None:
        """An upstream attempt against this engine failed (connect error or
        5xx, reported by the proxy's resilience layer)."""
        self.failed[engine_url] = self.failed.get(engine_url, 0) + 1

    def evict_url(self, engine_url: str) -> None:
        """Drop every per-engine aggregate for an engine that left the fleet
        for good (pod deleted / service removed) — the counterpart of the
        breaker registry's evict; without it pod churn grows these tables
        (and get_request_stats output) without bound."""
        for table in (
            self.qps_monitors, self.ttft_monitors, self.latency_monitors,
            self.decoding_length_monitors, self.itl_monitors,
            self.in_prefill, self.in_decoding, self.finished,
            self.swapped, self.failed,
        ):
            table.pop(engine_url, None)

    def get_request_stats(self, current_time: Optional[float] = None) -> Dict[str, RequestStats]:
        now = current_time if current_time is not None else time.time()
        urls = (
            set(self.qps_monitors)
            | set(self.in_prefill)
            | set(self.in_decoding)
            | set(self.finished)
        )
        out: Dict[str, RequestStats] = {}
        uptime = now - self.first_query_time if self.first_query_time else 0.0
        for url in urls:
            qps_mon = self.qps_monitors.get(url)
            qps = 0.0
            if qps_mon is not None:
                qps_mon.poll(now)
                qps = qps_mon.get_count() / self.window
            def avg(table: Dict[str, MovingAverageMonitor]) -> float:
                mon = table.get(url)
                return mon.get_average() if mon is not None else -1.0

            out[url] = RequestStats(
                qps=qps,
                ttft=avg(self.ttft_monitors),
                in_prefill_requests=self.in_prefill.get(url, 0),
                in_decoding_requests=self.in_decoding.get(url, 0),
                finished_requests=self.finished.get(url, 0),
                uptime=uptime,
                avg_decoding_length=avg(self.decoding_length_monitors),
                avg_latency=avg(self.latency_monitors),
                avg_itl=avg(self.itl_monitors),
                num_swapped_requests=self.swapped.get(url, 0),
                failed_requests=self.failed.get(url, 0),
            )
        return out


def initialize_request_stats_monitor(sliding_window_size: float) -> RequestStatsMonitor:
    return RequestStatsMonitor(sliding_window_size)


def get_request_stats_monitor() -> RequestStatsMonitor:
    return RequestStatsMonitor()
