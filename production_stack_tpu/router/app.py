"""Router app bootstrap: wire discovery, stats, routing, services; serve.

Capability parity with the reference's ``src/vllm_router/app.py``
(initialize_all :112-271, lifespan :83-109, main :283-299). aiohttp.web
replaces FastAPI/uvicorn; background workers are asyncio tasks started in
``on_startup``.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import Optional

import aiohttp
from aiohttp import web

from ..logging_utils import init_logger
from ..obs import (
    NOOP_TRACE,
    bind_log_context,
    configure_logging,
    error_headers,
    get_request_tracer,
    initialize_request_tracing,
    set_log_identity,
    teardown_request_tracing,
    unbind_log_context,
    update_log_context,
)
from ..resilience import (
    get_admission_controller,
    get_default_deadline_ms,
    get_retry_policy,
    get_tenant_config,
    initialize_resilience,
    teardown_resilience,
)
from ..resilience import metrics as res_metrics
from ..resilience.deadline import (
    DEADLINE_EXCEEDED_HEADER,
    min_attempt_budget,
    parse_deadline,
)
from ..obs.tasks import spawn_owned
from ..utils import parse_comma_separated, set_ulimit
from . import appscope
from .parser import parse_args
from .routes import routes
from .routing.logic import (
    RoutingLogic,
    initialize_routing_logic,
    teardown_routing_logic,
)
from .service_discovery import (
    ServiceDiscoveryType,
    initialize_service_discovery,
    teardown_service_discovery,
)
from .state import (
    PROVIDER_CANARY_TTFT,
    PROVIDER_ENDPOINTS,
    PROVIDER_FLEET_SNAPSHOT,
    PROVIDER_REQUEST_STATS,
    initialize_state_backend,
    teardown_state_backend,
)
from .stats.engine_stats import (
    EngineStatsScraper,
    bind_engine_stats_scraper,
    initialize_engine_stats_scraper,
    unbind_engine_stats_scraper,
)
from .stats.request_stats import (
    bind_request_stats_monitor,
    initialize_request_stats_monitor,
    unbind_request_stats_monitor,
)
from .services import metrics_service
from .services.callbacks import configure_custom_callbacks
from .services.canary import (
    initialize_canary_prober,
    teardown_canary_prober,
)
from .services.rewriter import initialize_request_rewriter
from .experimental.feature_gates import (
    PII_DETECTION,
    SEMANTIC_CACHE,
    get_feature_gates,
    initialize_feature_gates,
)

logger = init_logger(__name__)


async def _log_stats_loop(app: web.Application, interval: float) -> None:
    """Periodic human-readable fleet snapshot (reference log_stats.py:37-115)."""
    while True:
        await asyncio.sleep(interval)
        try:
            lines = ["", "=" * 60]
            # App-scoped, not the module default: the loop task runs
            # outside any request context, and with several router apps
            # in one process it must report ITS app's snapshot.
            engine_stats = app["engine_stats_scraper"].get_engine_stats()
            request_stats = app["request_stats_monitor"].get_request_stats(time.time())
            for ep in app["service_discovery"].get_endpoint_info():
                lines.append(f"Server: {ep.url} models={ep.model_names}")
                es = engine_stats.get(ep.url)
                if es:
                    lines.append(
                        f"  engine: running={es.num_running_requests} "
                        f"waiting={es.num_queuing_requests} "
                        f"kv_hit_rate={es.gpu_prefix_cache_hit_rate:.2f} "
                        f"kv_usage={es.gpu_cache_usage_perc:.2f}"
                    )
                rs = request_stats.get(ep.url)
                if rs:
                    lines.append(
                        f"  requests: qps={rs.qps:.2f} ttft={rs.ttft:.3f}s "
                        f"latency={rs.avg_latency:.3f}s itl={rs.avg_itl:.4f}s "
                        f"prefill={rs.in_prefill_requests} "
                        f"decode={rs.in_decoding_requests} "
                        f"finished={rs.finished_requests}"
                    )
            lines.append("=" * 60)
            logger.info("\n".join(lines))
        except Exception as e:  # noqa: BLE001
            logger.error("log_stats loop error: %s", e)


# Endpoints admission control protects (everything that fans into
# route_general_request — i.e. work an engine would have to execute).
_ADMISSION_PATHS = {
    "/v1/chat/completions", "/v1/completions", "/v1/embeddings",
    "/v1/rerank", "/rerank", "/v1/score", "/score",
    "/tokenize", "/detokenize",
}


@web.middleware
async def tracing_middleware(request: web.Request, handler):
    """Outermost middleware: request identity + the root span.

    Assigns (or adopts) the ``X-Request-Id``, opens the request's root
    span — joining the client's W3C trace when a valid ``traceparent``
    came in — and guarantees ``X-Request-Id`` on EVERY unprepared
    response: success, 429/504 sheds, 502 exhausted failover, 401s.
    Failures must be joinable to traces, not just the happy path.
    """
    request_id = request.headers.get("X-Request-Id") or str(uuid.uuid4())
    request["request_id"] = request_id
    trace = None
    recorder = get_request_tracer()
    if (
        recorder is not None
        and request.method == "POST"
        and request.path in _ADMISSION_PATHS
    ):
        trace = recorder.trace(
            request_id,
            headers=request.headers,
            attributes={"http.target": request.path},
        )
        request["trace"] = trace
    # Structured-log correlation (docs/observability.md "Structured
    # logging"): every log line emitted under this request — by any of
    # the ~50 init_logger modules, with zero call-site churn — carries
    # the same trace/request identity the spans and exemplars do.
    log_token = bind_log_context(
        request_id=request_id,
        trace_id=trace.trace_id if trace is not None else None,
    )
    status: Optional[int] = None
    try:
        response = await handler(request)
        status = response.status
        if not response.prepared:
            response.headers.setdefault("X-Request-Id", request_id)
        return response
    finally:
        unbind_log_context(log_token)
        if trace is not None:
            trace.finish(status=status)


@web.middleware
async def state_middleware(request: web.Request, handler):
    """Bind this app's injected singletons into request context and gate
    router-level drain.

    The request-stats monitor is an app-factory dependency (no longer a
    process singleton): binding it per request lets two router apps share
    one process — multi-replica tests — without stats bleed, while every
    downstream call site keeps using ``get_request_stats_monitor()``.

    Router drain (``POST /router/drain``, rolling restarts): new
    admission-path work is refused with 503 + ``X-PST-Router-Draining``
    while in-flight requests run to completion; ``/ready`` flips 503 so
    the load balancer stops sending traffic here.
    """
    # The app IS the scope: every ambient lookup (discovery, routing
    # logic, state backend, canary, gates, ...) under this request
    # resolves the serving app's instances, never another replica's.
    scope_token = appscope.bind_scope(request.app)
    monitor = request.app.get("request_stats_monitor")
    token = (
        bind_request_stats_monitor(monitor) if monitor is not None else None
    )
    scraper = request.app.get("engine_stats_scraper")
    scraper_token = (
        bind_engine_stats_scraper(scraper) if scraper is not None else None
    )
    try:
        if (
            request.app.get("router_draining")
            and request.method == "POST"
            and request.path in _ADMISSION_PATHS
        ):
            return web.json_response(
                {
                    "error": {
                        "message": "router replica is draining",
                        "type": "service_unavailable",
                        "code": 503,
                    }
                },
                status=503,
                headers=error_headers(
                    request, extra={"X-PST-Router-Draining": "1"}
                ),
            )
        return await handler(request)
    finally:
        if scraper_token is not None:
            unbind_engine_stats_scraper(scraper_token)
        if token is not None:
            unbind_request_stats_monitor(token)
        appscope.unbind_scope(scope_token)


@web.middleware
async def admission_middleware(request: web.Request, handler):
    """Token-bucket + bounded-priority-queue admission ahead of routing.

    Over-limit traffic is shed with 429 + ``Retry-After`` (deadline-based:
    a request that cannot get a token before its queue timeout is rejected
    immediately instead of parking). Requests carrying an end-to-end
    budget (``X-PST-Deadline-Ms``) cap their queue wait at the remaining
    budget, and a dequeue whose budget can no longer fit even the connect
    phase is shed with **504** (``expired``) instead of forwarded — the
    request was admitted, but only to die downstream.
    """
    if request.method == "POST" and request.path in _ADMISSION_PATHS:
        trace = request.get("trace") or NOOP_TRACE
        # The admission stage: budget parse + token-bucket/queue wait.
        span = trace.span("admission")
        # Tenant identity FIRST (docs/multi-tenancy.md): derived from the
        # API key (authenticated) or the tenant header, before any
        # overload decision — admission shares, deadline defaults, queue
        # order, engine scheduling and fleet scoring all key on it. The
        # resolved identity is re-stamped on every upstream hop, so a
        # client can never self-assign a class the config didn't grant.
        tenant = None
        tenant_cfg = get_tenant_config()
        if tenant_cfg is not None:
            auth = request.headers.get("Authorization", "")
            api_key = auth[7:] if auth.startswith("Bearer ") else None
            tenant = tenant_cfg.resolve(request.headers, api_key)
            request["tenant"] = tenant
            span.set_attribute("tenant", tenant.name)
            span.set_attribute("tenant_tier", tenant.tier)
            # The bounded label, not the raw name: log pipelines index
            # tenant like Prometheus does (ad-hoc names -> "other").
            update_log_context(tenant=tenant.label)
        # Parse the budget once, here, for every downstream consumer
        # (admission, routing, proxy attempts) — the monotonic deadline is
        # anchored at arrival, so queue time counts against the budget.
        # Tenant deadline defaults beat the global default: a batch
        # tenant can run deadline-free while interactive tenants inherit
        # a tight budget.
        default_ms = get_default_deadline_ms()
        if tenant is not None and tenant.deadline_ms > 0:
            default_ms = tenant.deadline_ms
        deadline = parse_deadline(request.headers, default_ms)
        if deadline is not None:
            request["deadline"] = deadline
            span.set_attribute(
                "deadline_ms", round(max(deadline.remaining_ms(), 0.0), 1)
            )
            res_metrics.deadline_budget_ms.observe(
                max(deadline.remaining_ms(), 0.0)
            )
        controller = get_admission_controller()
        if controller is not None and controller.enabled:
            try:
                priority = int(request.headers.get("X-Request-Priority", "0"))
            except ValueError:
                priority = 0
            decision = await controller.admit(
                priority,
                deadline=deadline,
                min_budget=min_attempt_budget(get_retry_policy()),
                tenant=tenant,
            )
            if not decision.admitted:
                if decision.reason == "expired":
                    res_metrics.deadline_sheds_total.labels(
                        stage="router_queue"
                    ).inc()
                    span.set_attribute("outcome", "deadline_shed")
                    span.add_event("deadline_shed", stage="router_queue")
                    span.end()
                    return web.json_response(
                        {
                            "error": {
                                "message": (
                                    "deadline exceeded while queued for "
                                    "admission"
                                ),
                                "type": "deadline_exceeded",
                                "code": 504,
                            }
                        },
                        status=504,
                        headers=error_headers(
                            request,
                            extra={DEADLINE_EXCEEDED_HEADER: "1"},
                        ),
                    )
                span.set_attribute("outcome", "shed")
                span.add_event("admission_shed", reason=decision.reason)
                span.end()
                return web.json_response(
                    {
                        "error": {
                            "message": (
                                f"request shed by admission control "
                                f"({decision.reason}); retry after "
                                f"{decision.retry_after_header}s"
                            ),
                            "type": "rate_limit_exceeded",
                            "code": 429,
                        }
                    },
                    status=429,
                    headers=error_headers(
                        request,
                        extra={"Retry-After": decision.retry_after_header},
                    ),
                )
        span.set_attribute("outcome", "admitted")
        span.end()
    return await handler(request)


# Mutating admin endpoints: without auth these let any client drain the
# whole fleet (or sleep it), so when an api key is configured they are
# guarded like /v1. Read-only probes (/is_draining, /is_sleeping,
# /engines) stay open, same as /health and /metrics. /debug/requests is
# guarded too — per-request timelines (ids, backend URLs, error strings)
# are not aggregate telemetry.
_GUARDED_ADMIN_PATHS = {"/drain", "/undrain", "/sleep", "/wake_up",
                        "/debug/requests", "/debug/fleet", "/router/drain",
                        "/router/undrain", "/_state/gossip"}


@web.middleware
async def api_key_middleware(request: web.Request, handler):
    required = request.app.get("api_key")
    if required and (
        request.path.startswith("/v1") or request.path in _GUARDED_ADMIN_PATHS
    ):
        auth = request.headers.get("Authorization", "")
        if auth != f"Bearer {required}":
            return web.json_response(
                {"error": {"message": "invalid API key", "type": "authentication_error"}},
                status=401,
                headers=error_headers(request),
            )
    return await handler(request)


def initialize_all(app: web.Application, args) -> None:
    """Create all router services from parsed args (pre-event-loop).

    The app itself is bound as the ambient scope first (``appscope``), so
    every ``initialize_*`` below stores its instance ON THE APP — factory
    injection and ambient lookup are the same storage, and a second app
    initialized later cannot repoint this one's services."""
    appscope.bind_scope(app)
    # The state backend comes up FIRST: resilience (fleet-wide admission,
    # breaker replication) and routing (shared endpoint view) consult it
    # at initialization time. In-memory default = single-replica behavior.
    backend = initialize_state_backend(args)
    if args.service_discovery == "static":
        initialize_service_discovery(
            ServiceDiscoveryType.STATIC,
            app=app,
            urls=parse_comma_separated(args.static_backends),
            models=parse_comma_separated(args.static_models),
            aliases=args.static_aliases_parsed,
            model_labels=parse_comma_separated(args.static_model_labels) or None,
            model_types=parse_comma_separated(args.static_model_types) or None,
            static_backend_health_checks=args.static_backend_health_checks,
            health_check_interval=args.health_check_interval,
            pools=parse_comma_separated(getattr(args, "static_pools", None))
            or None,
            prefill_model_labels=parse_comma_separated(args.prefill_model_labels) or None,
            decode_model_labels=parse_comma_separated(args.decode_model_labels) or None,
        )
    else:
        initialize_service_discovery(
            ServiceDiscoveryType.K8S,
            app=app,
            namespace=args.k8s_namespace,
            port=args.k8s_port,
            label_selector=args.k8s_label_selector,
            k8s_service_discovery_type=args.k8s_service_discovery_type,
            prefill_model_labels=parse_comma_separated(args.prefill_model_labels) or None,
            decode_model_labels=parse_comma_separated(args.decode_model_labels) or None,
        )

    # Scraper and monitor are app-injected dependencies (state_middleware
    # binds both per request); initialize_* also sets the module default
    # so background loops and single-app processes resolve the same
    # instance.
    app["engine_stats_scraper"] = initialize_engine_stats_scraper(
        args.engine_stats_interval
    )
    monitor = initialize_request_stats_monitor(args.request_stats_window)
    app["request_stats_monitor"] = monitor
    backend.register_provider(PROVIDER_REQUEST_STATS, monitor.sync_snapshot)
    # THIS app's discovery, resolved through the app at call time (the
    # provider runs from the gossip loop, and a dynamic-config reload may
    # have replaced the instance since registration).
    backend.register_provider(
        PROVIDER_ENDPOINTS,
        lambda: app["service_discovery"].get_endpoint_urls(),
    )
    # (Fleet routing's bounded-load view needs no provider of its own:
    # the routed in-flight counts ride the request_stats digest and
    # scoring reads the fleet-merged monitor view — the former
    # endpoint_loads gossip key carried the same numbers twice and is
    # gone; docs/router-ha.md.)
    initialize_routing_logic(
        RoutingLogic(args.routing_logic),
        session_key=args.session_key,
        kv_aware_threshold=args.kv_aware_threshold,
        controller_url=args.cache_controller_url,
        tokenizer_name=args.tokenizer_name,
        fleet_eviction_ratio=getattr(args, "fleet_eviction_ratio", 0.5),
        fleet_load_factor=getattr(args, "fleet_load_factor", 2.0),
        prefill_model_labels=parse_comma_separated(args.prefill_model_labels) or None,
        decode_model_labels=parse_comma_separated(args.decode_model_labels) or None,
    )
    initialize_resilience(args)
    initialize_request_tracing(
        enabled=getattr(args, "tracing", True),
        buffer=getattr(args, "debug_requests_buffer", 256),
    )
    # SLO counters (pst_slo_*) measure against this TTFT target; the canary
    # prober starts with the event loop in on_startup.
    metrics_service.configure_slo(getattr(args, "slo_ttft_ms", 0.0))
    # Capacity signals (GET /autoscale/signal + pst_capacity_*): the
    # in-process burn-rate/queue-slope/headroom monitor, fed by the same
    # SLO events the counters export (docs/observability.md "Capacity
    # signals").
    from .services.capacity import initialize_capacity_monitor

    initialize_capacity_monitor(
        enabled=getattr(args, "capacity_signal", True)
    )
    prober = initialize_canary_prober(
        getattr(args, "canary_interval", 0.0),
        timeout=getattr(args, "canary_timeout", 5.0),
        # The fleet shares one key (helm apiKeySecret): probes must
        # authenticate to engines like real proxied traffic does.
        api_key=getattr(args, "api_key", None),
    )
    # Canary health rides gossip (docs/router-ha.md): each replica
    # publishes its own probe TTFTs so replicas whose probes diverge
    # (one saw the failure, one didn't) still SCORE every engine the
    # same way — fleet routing merges local + peer views pessimistically.
    backend.register_provider(PROVIDER_CANARY_TTFT, prober.ttft_view)
    # Fleet introspection (GET /debug/fleet): THIS app's snapshot rides
    # the fleet_snapshot digest key so every peer replica can serve the
    # merged deployment picture (docs/observability.md "Fleet
    # debugging").
    from .services.fleet import fleet_snapshot_provider

    backend.register_provider(
        PROVIDER_FLEET_SNAPSHOT, fleet_snapshot_provider(app)
    )
    # Structured-log identity: the replica id joins every JSON log line
    # to the gossip membership view.
    set_log_identity(component="router", replica_id=backend.replica_id())
    initialize_request_rewriter(args.request_rewriter)
    configure_custom_callbacks(args.callbacks)
    initialize_feature_gates(args.feature_gates)
    app["api_key"] = args.api_key
    app["args"] = args

    gates = get_feature_gates()
    if gates.enabled(SEMANTIC_CACHE):
        from .experimental.semantic_cache import install_semantic_cache

        install_semantic_cache(app, args)
    if gates.enabled(PII_DETECTION):
        from .experimental.pii import install_pii_check

        install_pii_check(app, args)
    if args.enable_batch_api:
        from .services.files_service import install_files_api
        from .services.batch_service import install_batch_api

        install_files_api(app, args)
        install_batch_api(app, args)


def create_app(args) -> web.Application:
    # Optional error reporting + tracing (reference app.py:123-130; both
    # no-op loudly when the SDKs are absent).
    from ..utils_tracing import init_otel, init_sentry

    init_sentry(
        getattr(args, "sentry_dsn", None),
        getattr(args, "sentry_traces_sample_rate", 0.0),
        getattr(args, "sentry_profile_session_sample_rate", 0.0),
    )
    init_otel("pst-router")

    app = web.Application(
        middlewares=[
            tracing_middleware,
            state_middleware,
            api_key_middleware,
            admission_middleware,
        ],
        client_max_size=64 * 2**20,
    )
    initialize_all(app, args)
    app.add_routes(routes)

    async def on_startup(app: web.Application) -> None:
        # Re-bind THIS app as the ambient scope: startup may run after
        # another app's create_app() rebound the caller's context, and
        # every background task spawned below inherits this binding
        # (contextvars propagate through create_task) — so the loops of
        # app 1 never resolve app 2's services.
        appscope.bind_scope(app)
        app["client_session"] = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=None),
            connector=aiohttp.TCPConnector(limit=0),
        )
        # App-scoped (see on_cleanup): each app starts ITS OWN services.
        await app["service_discovery"].start()
        await app["engine_stats_scraper"].start()
        backend = app.get("state_backend")
        if backend is not None:
            await backend.start(app)
        prober = app.get("canary_prober")
        if prober is not None:
            await prober.start()
        if args.log_stats:
            app["log_stats_task"] = spawn_owned(
                _log_stats_loop(app, args.log_stats_interval),
                name="router-log-stats",
            )
        if args.dynamic_config_json:
            from .dynamic_config import initialize_dynamic_config_watcher

            app["dynamic_config_watcher"] = initialize_dynamic_config_watcher(
                args.dynamic_config_json, 10.0, args, app
            )
        for key in ("batch_processor",):
            proc = app.get(key)
            if proc is not None:
                await proc.start()

    async def on_cleanup(app: web.Application) -> None:
        # Bind THIS app as the scope: cleanup may run from a context where
        # another app was initialized later, and every teardown below must
        # tear down OUR services, not the ambient context's.
        appscope.bind_scope(app)
        task = app.get("log_stats_task")
        if task is not None:
            task.cancel()
        proc = app.get("batch_processor")
        if proc is not None:
            await proc.close()
        watcher = app.get("dynamic_config_watcher")
        if watcher is not None:
            watcher.close()
        prober = app.get("canary_prober")
        if prober is not None:
            await prober.close()
        teardown_canary_prober()
        # Close the app's OWN scraper; with the app bound as scope the
        # teardown clears exactly this app's entry.
        app["engine_stats_scraper"].close()
        EngineStatsScraper.destroy()
        teardown_service_discovery()
        # Routers holding a long-lived client (kvaware, fleet) close it here.
        router = app.get("routing_logic")
        aclose = getattr(router, "aclose", None)
        if aclose is not None:
            await aclose()
        teardown_routing_logic()
        teardown_resilience()
        backend = app.get("state_backend")
        if backend is not None:
            await backend.close()
        teardown_state_backend()
        teardown_request_tracing()
        for key in ("client_session", "prefill_client", "decode_client"):
            session = app.get(key)
            if session is not None:
                await session.close()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def main(argv: Optional[list] = None) -> None:
    args = parse_args(argv)
    configure_logging(
        getattr(args, "log_format", "text") or "text", component="router"
    )
    set_ulimit()
    app = create_app(args)
    logger.info("starting pst-router on %s:%d", args.host, args.port)
    web.run_app(app, host=args.host, port=args.port, access_log=None)


if __name__ == "__main__":
    main()
