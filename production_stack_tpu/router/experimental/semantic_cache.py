"""Semantic cache: serve chat completions for semantically-equal prompts.

Capability parity with the reference's experimental semantic cache
(``experimental/semantic_cache/semantic_cache.py`` + FAISS adapter): embed
the chat messages, nearest-neighbor search with a similarity threshold,
serve the stored completion on a hit, store after proxying on a miss.

TPU-environment redesign: sentence-transformers/faiss are not available
(zero-egress image), so embeddings are pluggable:

- ``hash`` (default, dependency-free): token n-gram feature hashing into a
  dense normalized vector. Deterministic, catches near-duplicate prompts
  (the actual production win — repeated identical/boilerplate requests).
- ``engine``: embed via a backend's ``/v1/embeddings`` (the TPU engine
  serves real model embeddings), for true semantic similarity.

Search is exact cosine over a numpy matrix (fleets cache thousands, not
billions, of entries; brute-force at this scale beats an ANN index).
Persistence: ``.npz`` + responses JSONL under ``--semantic-cache-dir``.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Callable, List, Optional

import numpy as np
from aiohttp import web
from prometheus_client import Counter, REGISTRY

from ...logging_utils import init_logger

logger = init_logger(__name__)

_DIM = 256


def _metric(name: str, doc: str) -> Counter:
    try:
        return Counter(name, doc)
    except ValueError:  # re-registration in tests
        return REGISTRY._names_to_collectors[name]  # type: ignore[return-value]


hits_total = _metric("pst_router_semantic_cache_hits_total", "semantic cache hits")
misses_total = _metric("pst_router_semantic_cache_misses_total", "semantic cache misses")


def hash_embed(text: str, dim: int = _DIM) -> np.ndarray:
    """Feature-hashed word 1/2-gram embedding (dependency-free)."""
    import xxhash

    vec = np.zeros(dim, np.float32)
    words = text.lower().split()
    for i, w in enumerate(words):
        vec[xxhash.xxh32_intdigest(w) % dim] += 1.0
        if i + 1 < len(words):
            vec[xxhash.xxh32_intdigest(w + " " + words[i + 1]) % dim] += 1.0
    n = float(np.linalg.norm(vec))
    return vec / n if n > 0 else vec


class EngineEmbedder:
    """Embed via a backend's ``/v1/embeddings`` — real model embeddings for
    true semantic similarity (the reference's sentence-transformers role,
    served by the TPU engine's encode path instead)."""

    def __init__(self, app, model: Optional[str] = None, timeout: float = 5.0):
        self._app = app
        self.model = model  # None: pin to the first model that answers
        self.timeout = timeout
        # One index = one vector space: without an explicit model, the
        # first successful embed pins the model; endpoint flips must not
        # silently switch embedding spaces mid-index.
        self._pinned: Optional[str] = model

    async def __call__(self, text: str) -> Optional[np.ndarray]:
        from ..service_discovery import get_service_discovery

        session = self._app.get("client_session")
        if session is None:
            return None
        try:
            endpoints = get_service_discovery().get_endpoint_info()
        except Exception:  # noqa: BLE001 — discovery not up yet
            return None
        for ep in endpoints:
            if getattr(ep, "sleep", False):
                continue
            models = getattr(ep, "model_names", None) or []
            model = self._pinned or (models[0] if models else None)
            if not model or (self._pinned and self._pinned not in models):
                continue
            try:
                # pstlint: disable=hop-contract(cache-fill embeddings are router-internal traffic keyed by text and shared across clients; stamping one client's request id would mis-attribute every later cache hit)
                async with session.post(
                    f"{ep.url.rstrip('/')}/v1/embeddings",
                    json={"model": model, "input": [text[:8192]]},
                    timeout=self.timeout,
                ) as resp:
                    if resp.status != 200:
                        continue
                    data = await resp.json()
                vec = np.asarray(
                    data["data"][0]["embedding"], np.float32
                )
                self._pinned = model
                n = float(np.linalg.norm(vec))
                return vec / n if n > 0 else vec
            except Exception:  # noqa: BLE001 — try the next endpoint
                continue
        return None


class SemanticCache:
    def __init__(
        self, cache_dir: Optional[str], threshold: float,
        persist_interval: float = 5.0,
        embedder: str = "auto",
        engine_embed: Optional[EngineEmbedder] = None,
    ):
        self.threshold = threshold
        self.cache_dir = cache_dir
        self.persist_interval = persist_interval
        self._last_persist = 0.0
        # Embedder selection (VERDICT r3 #9): "engine" = real embeddings
        # via /v1/embeddings; "hash" = dependency-free lexical features;
        # "auto" = probe once at first use — engine when a backend answers
        # /v1/embeddings, else hash. The persisted index is tagged with the
        # embedder that built it (mixing vector spaces would be garbage).
        self.embedder = embedder
        self.engine_embed = engine_embed
        self._mode: Optional[str] = (
            None if embedder == "auto" else embedder
        )
        # Embedding model the index was built with (engine mode only).
        # Persisted alongside the embedder tag: in engine mode with no
        # explicit --semantic-cache-embed-model, a restart can pin a
        # different served model with the same dimension — same-dim but
        # different vector spaces must not silently mix.
        self._index_model: Optional[str] = None
        self.vectors = np.zeros((0, _DIM), np.float32)
        self.entries: List[dict] = []  # {"model":..., "response": body-json}
        self._lock = asyncio.Lock()
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
            self._load()

    async def _embed(self, text: str) -> Optional[np.ndarray]:
        """Embed under the selected mode, deciding the mode on first use."""
        if self._mode is None:
            vec = (
                await self.engine_embed(text)
                if self.engine_embed is not None
                else None
            )
            self._mode = "engine" if vec is not None else "hash"
            logger.info("semantic cache: auto-selected %r embedder", self._mode)
            if self._mode == "engine":
                self._reset_if_dim_mismatch(vec.shape[0])
                self._reset_if_model_mismatch(
                    getattr(self.engine_embed, "_pinned", None)
                )
                return vec
        if self._mode == "engine":
            vec = await self.engine_embed(text) if self.engine_embed else None
            if vec is not None:
                self._reset_if_dim_mismatch(vec.shape[0])
                self._reset_if_model_mismatch(
                    getattr(self.engine_embed, "_pinned", None)
                )
            return vec  # None: backend briefly unavailable -> skip cache
        vec = hash_embed(text)
        self._reset_if_dim_mismatch(vec.shape[0])
        return vec

    def _reset_if_model_mismatch(self, model: Optional[str]) -> None:
        if model is None:
            return
        if self._index_model is None:
            self._index_model = model
            return
        if self._index_model != model:
            if len(self.entries):
                logger.warning(
                    "semantic cache: embedding model changed (%r -> %r); "
                    "dropping %d entries",
                    self._index_model, model, len(self.entries),
                )
            self.vectors = np.zeros((0, self.vectors.shape[1]), np.float32)
            self.entries = []
            self._index_model = model

    def _reset_if_dim_mismatch(self, dim: int) -> None:
        if self.vectors.shape[1] != dim:
            if len(self.entries):
                logger.warning(
                    "semantic cache: embedder dim changed (%d -> %d); "
                    "dropping %d entries",
                    self.vectors.shape[1], dim, len(self.entries),
                )
            self.vectors = np.zeros((0, dim), np.float32)
            self.entries = []

    # -- persistence ------------------------------------------------------

    def _load(self) -> None:
        npz = os.path.join(self.cache_dir, "vectors.npz")
        jl = os.path.join(self.cache_dir, "entries.jsonl")
        if os.path.exists(npz) and os.path.exists(jl):
            try:
                loaded = np.load(npz, allow_pickle=False)
                saved_mode = str(loaded["embedder"]) if "embedder" in loaded else "hash"
                if self._mode is not None and saved_mode != self._mode:
                    logger.warning(
                        "semantic cache: on-disk index built with %r embedder, "
                        "current mode %r — starting empty", saved_mode, self._mode
                    )
                    return
                if self._mode is None:
                    # auto: adopt the persisted index's vector space — a
                    # later hash fallback must not mix into engine vectors.
                    self._mode = saved_mode
                    logger.info(
                        "semantic cache: adopting persisted %r embedder",
                        saved_mode,
                    )
                saved_model = (
                    str(loaded["model"]) if "model" in loaded else ""
                )
                self._index_model = saved_model or None
                self.vectors = loaded["vectors"]
                with open(jl) as f:
                    self.entries = [json.loads(line) for line in f]
                logger.info("semantic cache: loaded %d entries", len(self.entries))
            except Exception as e:  # noqa: BLE001
                logger.warning("semantic cache load failed: %s", e)

    def _persist_snapshot(self, vectors: np.ndarray, entries: List[dict]) -> None:
        np.savez(
            os.path.join(self.cache_dir, "vectors.npz"),
            vectors=vectors,
            embedder=np.asarray(self._mode or "hash"),
            model=np.asarray(self._index_model or ""),
        )
        with open(os.path.join(self.cache_dir, "entries.jsonl"), "w") as f:
            for e in entries:
                f.write(json.dumps(e) + "\n")

    # -- core -------------------------------------------------------------

    @staticmethod
    def request_text(request_json: dict) -> str:
        parts = []
        for m in request_json.get("messages", []):
            content = m.get("content")
            if isinstance(content, str):
                parts.append(f"{m.get('role', 'user')}: {content}")
        return "\n".join(parts)

    async def check(self, request_json: dict) -> Optional[dict]:
        if request_json.get("stream"):
            return None  # cached bodies are full JSON, not SSE
        text = self.request_text(request_json)
        if not text:
            return None
        vec = await self._embed(text)
        if vec is None:
            misses_total.inc()
            return None
        async with self._lock:
            if len(self.entries) == 0:
                misses_total.inc()
                return None
            sims = self.vectors @ vec
            best = int(np.argmax(sims))
            if float(sims[best]) >= self.threshold and (
                self.entries[best]["model"] == request_json.get("model")
            ):
                hits_total.inc()
                return self.entries[best]["response"]
        misses_total.inc()
        return None

    async def store(self, request_json: dict, response_body: dict) -> None:
        text = self.request_text(request_json)
        if not text:
            return
        vec = await self._embed(text)
        if vec is None:
            return
        async with self._lock:
            self.vectors = np.vstack([self.vectors, vec[None, :]])
            self.entries.append(
                {"model": request_json.get("model"), "response": response_body,
                 "ts": time.time()}
            )
        # Persist off-loop and throttled: a full rewrite per miss would be
        # O(n²) I/O on the event loop.
        now = time.time()
        if self.cache_dir and now - self._last_persist >= self.persist_interval:
            self._last_persist = now
            vectors = self.vectors
            entries = list(self.entries)
            await asyncio.get_event_loop().run_in_executor(
                None, self._persist_snapshot, vectors, entries
            )


def install_semantic_cache(app: web.Application, args) -> None:
    embedder = getattr(args, "semantic_cache_embedder", "auto")
    cache = SemanticCache(
        args.semantic_cache_dir,
        args.semantic_cache_threshold,
        embedder=embedder,
        engine_embed=(
            EngineEmbedder(
                app, getattr(args, "semantic_cache_embed_model", None)
            )
            if embedder in ("auto", "engine")
            else None
        ),
    )
    app["semantic_cache"] = cache

    async def check(request_json: dict) -> Optional[web.Response]:
        cached = await cache.check(request_json)
        if cached is None:
            return None
        return web.json_response(cached, headers={"X-Semantic-Cache": "hit"})

    async def store(request: web.Request, content: bytes) -> None:
        # Only cache non-streamed successful chat completions.
        if request.path != "/v1/chat/completions":
            return
        try:
            body = json.loads(content)
        except (json.JSONDecodeError, UnicodeDecodeError):
            return
        if "choices" not in body:
            return
        request_json = request.get("parsed_json") or {}
        if request_json.get("stream"):
            return
        await cache.store(request_json, body)

    app["semantic_cache_check"] = check
    app["semantic_cache_store"] = store
    logger.info(
        "semantic cache enabled (threshold %.2f, dir %s)",
        args.semantic_cache_threshold, args.semantic_cache_dir,
    )
