"""Feature gates for experimental router subsystems.

Capability parity with the reference's
``src/vllm_router/experimental/feature_gates.py:46-104``:
``--feature-gates SemanticCache=true,PIIDetection=true`` with
Alpha/Beta/GA stages and a singleton registry.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, Optional

from ...logging_utils import init_logger

logger = init_logger(__name__)


class FeatureStage(enum.Enum):
    ALPHA = "Alpha"
    BETA = "Beta"
    GA = "GA"


@dataclass(frozen=True)
class Feature:
    name: str
    stage: FeatureStage
    default: bool


SEMANTIC_CACHE = "SemanticCache"
PII_DETECTION = "PIIDetection"

KNOWN_FEATURES: Dict[str, Feature] = {
    SEMANTIC_CACHE: Feature(SEMANTIC_CACHE, FeatureStage.ALPHA, False),
    PII_DETECTION: Feature(PII_DETECTION, FeatureStage.ALPHA, False),
}


class FeatureGates:
    def __init__(self, spec: Optional[str] = None):
        self._enabled: Dict[str, bool] = {
            name: f.default for name, f in KNOWN_FEATURES.items()
        }
        for pair in (spec or "").split(","):
            pair = pair.strip()
            if not pair:
                continue
            if "=" not in pair:
                raise ValueError(f"bad feature gate {pair!r}, expected Name=true|false")
            name, value = pair.split("=", 1)
            name = name.strip()
            if name not in KNOWN_FEATURES:
                raise ValueError(
                    f"unknown feature gate {name!r}; known: {sorted(KNOWN_FEATURES)}"
                )
            self._enabled[name] = value.strip().lower() in ("true", "1", "yes")
            logger.info(
                "feature gate %s=%s (stage %s)",
                name,
                self._enabled[name],
                KNOWN_FEATURES[name].stage.value,
            )

    def enabled(self, name: str) -> bool:
        return self._enabled.get(name, False)


# App-scoped (router.appscope): gates are per app, not per process.
_SCOPE_KEY = "feature_gates"


def initialize_feature_gates(spec: Optional[str] = None) -> FeatureGates:
    from .. import appscope

    return appscope.scoped_set(_SCOPE_KEY, FeatureGates(spec))


def get_feature_gates() -> FeatureGates:
    from .. import appscope

    gates = appscope.scoped_get(_SCOPE_KEY)
    if gates is None:
        gates = appscope.scoped_set(_SCOPE_KEY, FeatureGates())
    return gates
