"""PII detection gate: scan request content, block on detection.

Capability parity with the reference's experimental PII middleware
(``experimental/pii/``: regex + Presidio analyzers behind a factory,
``analyzers/factory.py`` + ``analyzers/presidio.py:45``, block-on-detect
with Prometheus counters). Two analyzers behind :func:`create_analyzer`:

- ``regex`` (shipped default): pattern classes re-derived from the
  reference's set — email / phone / SSN / credit card (Luhn-validated) /
  IP / API-key shapes.
- ``presidio``: the NER-based Presidio AnalyzerEngine when the optional
  ``presidio-analyzer`` package is installed; selection fails loudly (at
  startup, not per request) when it is not.
"""

from __future__ import annotations

import asyncio
import re
from typing import Dict, List, Optional, Pattern

from aiohttp import web
from prometheus_client import Counter, REGISTRY

from ...logging_utils import init_logger
from ...obs import error_headers

logger = init_logger(__name__)


def _metric(name: str, doc: str, labels: List[str]) -> Counter:
    try:
        return Counter(name, doc, labels)
    except ValueError:
        return REGISTRY._names_to_collectors[name]  # type: ignore[return-value]


pii_detected_total = _metric(
    "pst_router_pii_detected_total", "requests blocked for PII", ["pii_type"]
)

PII_PATTERNS: Dict[str, Pattern[str]] = {
    "email": re.compile(r"\b[\w.+-]+@[\w-]+\.[\w.-]{2,}\b"),
    "phone": re.compile(r"\b(?:\+?\d{1,3}[ .-]?)?(?:\(\d{2,4}\)[ .-]?)?\d{3}[ .-]\d{3,4}[ .-]?\d{0,4}\b"),
    "ssn": re.compile(r"\b\d{3}-\d{2}-\d{4}\b"),
    "credit_card": re.compile(r"\b(?:\d[ -]?){13,19}\b"),
    "ipv4": re.compile(r"\b(?:\d{1,3}\.){3}\d{1,3}\b"),
    "api_key": re.compile(r"\b(?:sk|pk|rk)[-_][A-Za-z0-9]{16,}\b"),
}


def _luhn_ok(digits: str) -> bool:
    ds = [int(c) for c in digits if c.isdigit()]
    if not 13 <= len(ds) <= 19:
        return False
    total = 0
    for i, d in enumerate(reversed(ds)):
        if i % 2 == 1:
            d *= 2
            if d > 9:
                d -= 9
        total += d
    return total % 10 == 0


class RegexPIIAnalyzer:
    """Pattern scan; credit-card candidates additionally Luhn-validated."""

    def __init__(self, types: Optional[List[str]] = None):
        if types is not None:
            unknown = set(types) - set(PII_PATTERNS)
            if unknown:
                # A typo must not silently disable the gate.
                raise ValueError(
                    f"unknown PII types {sorted(unknown)}; "
                    f"valid: {sorted(PII_PATTERNS)}"
                )
        self.patterns = {
            k: v for k, v in PII_PATTERNS.items() if types is None or k in types
        }

    def analyze(self, text: str) -> List[str]:
        found = []
        for name, pattern in self.patterns.items():
            for match in pattern.finditer(text):
                if name == "credit_card" and not _luhn_ok(match.group()):
                    continue
                found.append(name)
                break
        return found


class PresidioPIIAnalyzer:
    """NER-based analyzer (reference ``analyzers/presidio.py:45``): wraps
    presidio-analyzer's AnalyzerEngine, mapping its entity names onto the
    same type labels the regex analyzer emits so metrics stay comparable."""

    ENTITY_MAP = {
        "EMAIL_ADDRESS": "email",
        "PHONE_NUMBER": "phone",
        "US_SSN": "ssn",
        "CREDIT_CARD": "credit_card",
        "IP_ADDRESS": "ipv4",
        "PERSON": "person",
        "LOCATION": "location",
    }

    def __init__(self, types: Optional[List[str]] = None,
                 score_threshold: float = 0.5):
        from presidio_analyzer import AnalyzerEngine  # optional dependency

        if types is not None:
            valid = set(self.ENTITY_MAP.values())
            unknown = set(types) - valid
            if unknown:
                raise ValueError(
                    f"unknown PII types {sorted(unknown)}; "
                    f"valid: {sorted(valid)}"
                )
        self._engine = AnalyzerEngine()
        self._threshold = score_threshold
        # Entity filter pushed INTO the engine: unrequested recognizers
        # (the NER ones are the expensive passes) never run.
        self._entities = (
            [e for e, n in self.ENTITY_MAP.items() if n in set(types)]
            if types else None
        )

    def analyze(self, text: str) -> List[str]:
        found = []
        results = self._engine.analyze(
            text=text, language="en", entities=self._entities,
            score_threshold=self._threshold,
        )
        for res in results:
            name = self.ENTITY_MAP.get(res.entity_type, res.entity_type.lower())
            if name not in found:
                found.append(name)
        return found


def create_analyzer(kind: str = "regex", types: Optional[List[str]] = None):
    """Analyzer factory (reference ``analyzers/factory.py``)."""
    if kind == "regex":
        return RegexPIIAnalyzer(types)
    if kind == "presidio":
        try:
            return PresidioPIIAnalyzer(types)
        except ImportError as e:
            raise RuntimeError(
                "--pii-analyzer presidio requires the optional "
                "presidio-analyzer package (pip install presidio-analyzer)"
            ) from e
    raise ValueError(f"unknown PII analyzer {kind!r} (regex|presidio)")


def extract_text(request_json: dict) -> str:
    parts: List[str] = []
    prompt = request_json.get("prompt")
    if isinstance(prompt, str):
        parts.append(prompt)
    elif isinstance(prompt, list):
        parts.extend(p for p in prompt if isinstance(p, str))
    for m in request_json.get("messages", []):
        content = m.get("content") if isinstance(m, dict) else None
        if isinstance(content, str):
            parts.append(content)
    return "\n".join(parts)


def install_pii_check(app: web.Application, args) -> None:
    types = getattr(args, "pii_types", None)
    if isinstance(types, str):
        types = [t.strip() for t in types.split(",") if t.strip()] or None
    analyzer = create_analyzer(
        getattr(args, "pii_analyzer", "regex") or "regex", types
    )
    app["pii_analyzer"] = analyzer

    async def check(request_json: dict) -> Optional[web.Response]:
        text = extract_text(request_json)
        if not text:
            return None
        # Off the event loop: presidio's NER inference is CPU-bound for
        # tens-to-hundreds of ms (and regex over long prompts isn't free) —
        # inline it would serialize every in-flight request behind the scan.
        found = await asyncio.get_running_loop().run_in_executor(
            None, analyzer.analyze, text
        )
        if not found:
            return None
        for t in found:
            pii_detected_total.labels(pii_type=t).inc()
        logger.warning("request blocked: PII detected (%s)", ", ".join(found))
        return web.json_response(
            {
                "error": {
                    "message": f"request blocked: detected PII ({', '.join(sorted(found))})",
                    "type": "pii_detected",
                    "code": 400,
                }
            },
            status=400,
            # No live request object here (the check sees parsed JSON
            # only): the builder returns {} and the tracing middleware's
            # setdefault stamps the real id on the way out.
            headers=error_headers(None),
        )

    app["pii_check"] = check
    logger.info(
        "PII detection enabled (%s analyzer)", type(analyzer).__name__
    )
