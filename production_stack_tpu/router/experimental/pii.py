"""PII detection gate: scan request content, block on detection.

Capability parity with the reference's experimental PII middleware
(``experimental/pii/``: regex + Presidio analyzers, block-on-detect with
Prometheus counters). Presidio is unavailable in this image, so the analyzer
surface is pluggable with the regex analyzer as the shipped implementation
(the reference's regex pattern classes, re-derived: email / phone / SSN /
credit card / IP / API-key shapes).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Pattern

from aiohttp import web
from prometheus_client import Counter, REGISTRY

from ...logging_utils import init_logger

logger = init_logger(__name__)


def _metric(name: str, doc: str, labels: List[str]) -> Counter:
    try:
        return Counter(name, doc, labels)
    except ValueError:
        return REGISTRY._names_to_collectors[name]  # type: ignore[return-value]


pii_detected_total = _metric(
    "pst_router_pii_detected_total", "requests blocked for PII", ["pii_type"]
)

PII_PATTERNS: Dict[str, Pattern[str]] = {
    "email": re.compile(r"\b[\w.+-]+@[\w-]+\.[\w.-]{2,}\b"),
    "phone": re.compile(r"\b(?:\+?\d{1,3}[ .-]?)?(?:\(\d{2,4}\)[ .-]?)?\d{3}[ .-]\d{3,4}[ .-]?\d{0,4}\b"),
    "ssn": re.compile(r"\b\d{3}-\d{2}-\d{4}\b"),
    "credit_card": re.compile(r"\b(?:\d[ -]?){13,19}\b"),
    "ipv4": re.compile(r"\b(?:\d{1,3}\.){3}\d{1,3}\b"),
    "api_key": re.compile(r"\b(?:sk|pk|rk)[-_][A-Za-z0-9]{16,}\b"),
}


def _luhn_ok(digits: str) -> bool:
    ds = [int(c) for c in digits if c.isdigit()]
    if not 13 <= len(ds) <= 19:
        return False
    total = 0
    for i, d in enumerate(reversed(ds)):
        if i % 2 == 1:
            d *= 2
            if d > 9:
                d -= 9
        total += d
    return total % 10 == 0


class RegexPIIAnalyzer:
    """Pattern scan; credit-card candidates additionally Luhn-validated."""

    def __init__(self, types: Optional[List[str]] = None):
        self.patterns = {
            k: v for k, v in PII_PATTERNS.items() if types is None or k in types
        }

    def analyze(self, text: str) -> List[str]:
        found = []
        for name, pattern in self.patterns.items():
            for match in pattern.finditer(text):
                if name == "credit_card" and not _luhn_ok(match.group()):
                    continue
                found.append(name)
                break
        return found


def extract_text(request_json: dict) -> str:
    parts: List[str] = []
    prompt = request_json.get("prompt")
    if isinstance(prompt, str):
        parts.append(prompt)
    elif isinstance(prompt, list):
        parts.extend(p for p in prompt if isinstance(p, str))
    for m in request_json.get("messages", []):
        content = m.get("content") if isinstance(m, dict) else None
        if isinstance(content, str):
            parts.append(content)
    return "\n".join(parts)


def install_pii_check(app: web.Application, args) -> None:
    analyzer = RegexPIIAnalyzer()
    app["pii_analyzer"] = analyzer

    async def check(request_json: dict) -> Optional[web.Response]:
        text = extract_text(request_json)
        if not text:
            return None
        found = analyzer.analyze(text)
        if not found:
            return None
        for t in found:
            pii_detected_total.labels(pii_type=t).inc()
        logger.warning("request blocked: PII detected (%s)", ", ".join(found))
        return web.json_response(
            {
                "error": {
                    "message": f"request blocked: detected PII ({', '.join(sorted(found))})",
                    "type": "pii_detected",
                    "code": 400,
                }
            },
            status=400,
        )

    app["pii_check"] = check
    logger.info("PII detection enabled (regex analyzer)")
