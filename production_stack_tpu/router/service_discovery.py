"""Engine endpoint discovery: static lists and Kubernetes watchers.

Capability parity with the reference's ``src/vllm_router/service_discovery.py``
(EndpointInfo :80-175, StaticServiceDiscovery :206-341, K8sPodIPServiceDiscovery
:344-746, K8sServiceNameServiceDiscovery :749-1150, factory :1153-1229).

Redesign notes (not a translation):
- asyncio-native: watchers are asyncio tasks on the app loop, not daemon
  threads with their own event loops.
- No ``kubernetes`` client dependency: a minimal in-cluster K8s API client
  (service-account token + CA, aiohttp watch streams) lives in
  :mod:`production_stack_tpu.router.k8s_client`.
"""

# pstlint: disable-file=hop-contract(discovery health/ready/drain/model probes are control-plane traffic on the reconcile loops; no client request context exists to propagate)
from __future__ import annotations

import asyncio
import enum
import hashlib
import time
import uuid
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import aiohttp

from ..logging_utils import init_logger
from ..obs.tasks import spawn_owned
from ..utils import ModelType

logger = init_logger(__name__)


class ServiceDiscoveryType(enum.Enum):
    STATIC = "static"
    K8S = "k8s"


def warming_from_ready(status: int, body) -> bool:
    """Interpret one engine ``/ready`` response (the single source for
    both discovery modes): warming iff it is a 503 whose JSON body says
    ``reason == "warming"``. 200 (ready), 404 (pre-warmup engine without
    the endpoint), and unparseable bodies are not-warming — a draining or
    unhealthy 503 is handled by its own probes."""
    if status in (200, 404) or not isinstance(body, dict):
        return False
    return body.get("reason") == "warming"


async def probe_warming(
    session: aiohttp.ClientSession, base_url: str, timeout: float = 5.0
) -> Optional[bool]:
    """One GET /ready against an engine, interpreted by
    ``warming_from_ready``. Tri-state: True/False, or None when the probe
    itself failed (timeout / connect error) — callers keep the last-known
    state rather than flapping a warming engine back to routable."""
    try:
        async with session.get(
            f"{base_url}/ready", timeout=aiohttp.ClientTimeout(total=timeout)
        ) as resp:
            try:
                body = await resp.json()
            except Exception:  # noqa: BLE001 — non-JSON 5xx
                body = None
            return warming_from_ready(resp.status, body)
    except Exception:  # noqa: BLE001
        return None


def _pool_label(labels: Dict[str, str]) -> str:
    """Declared disagg pool from pod/service labels (helm stamps
    ``pst-pool`` from ``servingEngineSpec.pool``); anything unrecognized
    is fused — the safe shape."""
    pool = (labels.get("pst-pool") or labels.get("pool") or "").strip().lower()
    return pool if pool in ("prefill", "decode") else "fused"


@dataclass
class ModelInfo:
    """A model (base or LoRA adapter) served by an endpoint."""

    id: str
    object: str = "model"
    created: int = field(default_factory=lambda: int(time.time()))
    owned_by: str = "production-stack-tpu"
    parent: Optional[str] = None
    root: Optional[str] = None
    is_adapter: bool = False

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModelInfo":
        return cls(
            id=d.get("id", ""),
            object=d.get("object", "model"),
            created=d.get("created", int(time.time())),
            owned_by=d.get("owned_by", "unknown"),
            parent=d.get("parent"),
            root=d.get("root"),
            is_adapter=d.get("parent") is not None,
        )


@dataclass
class EndpointInfo:
    """One serving-engine endpoint, as seen by the router.

    Field parity with the reference's EndpointInfo
    (``service_discovery.py:80-175``).
    """

    url: str
    model_names: List[str]
    Id: str
    added_timestamp: float
    model_label: str
    sleep: bool = False
    # Graceful drain: the engine finishes in-flight sequences but accepts
    # no new ones — routing must treat it as unroutable (resilience
    # subsystem; no reference counterpart).
    draining: bool = False
    # Warmup precompilation in progress (engine /ready reports 503 with
    # reason "warming"): the engine is alive but routing traffic to it
    # would land requests behind the XLA compile storm — unroutable the
    # same way draining is, until /ready flips.
    warming: bool = False
    # Declared disagg pool (docs/disagg.md): "prefill" | "decode" |
    # "fused". Surfaced from helm's servingEngineSpec.pool (pod label
    # pst-pool), --static-pools, or defaulted — fused engines serve both
    # disagg legs, so mixed fleets degrade gracefully.
    pool: str = "fused"
    pod_name: Optional[str] = None
    service_name: Optional[str] = None
    namespace: Optional[str] = None
    model_info: Dict[str, ModelInfo] = field(default_factory=dict)

    def get_base_models(self) -> List[str]:
        return [mid for mid, mi in self.model_info.items() if not mi.parent]

    def get_adapters(self) -> List[str]:
        return [mid for mid, mi in self.model_info.items() if mi.parent]

    def get_adapters_for_model(self, base_model: str) -> List[str]:
        return [mid for mid, mi in self.model_info.items() if mi.parent == base_model]

    def has_model(self, model_id: str) -> bool:
        return model_id in self.model_names

    def get_model_info(self, model_id: str) -> Optional[ModelInfo]:
        return self.model_info.get(model_id)


class ServiceDiscovery(ABC):
    """Source of truth for which engine endpoints exist right now."""

    app = None  # set by factory; used for prefill/decode client sessions

    @abstractmethod
    def get_endpoint_info(self) -> List[EndpointInfo]:
        ...

    def get_health(self) -> bool:
        return True

    def set_draining(self, url: str, draining: bool) -> None:
        """Mark/unmark an endpoint as draining immediately.

        Router-initiated drain (the /drain fan-out) calls this so routing
        reacts at once; the periodic probes / watch events still reconcile
        drains initiated directly against an engine."""

    def set_warming(self, url: str, warming: bool) -> None:
        """Mark/unmark an endpoint as warming (precompiling) immediately —
        the probes / watch events reconcile against the engine's /ready."""

    def set_sleeping(self, url: str, sleeping: bool) -> None:
        """Mark/unmark an endpoint as slept immediately.

        Router-initiated sleep (the /sleep fan-out — the operator's
        scale-to-zero path, docs/autoscaling.md "Scale to zero") calls
        this so the standby stops receiving traffic BEFORE the engine
        acks the sleep; the probes / watch events reconcile against the
        engine's /is_sleeping."""

    async def start(self) -> None:
        """Begin background watch/health tasks (called from app startup)."""

    def close(self) -> None:
        """Stop background tasks."""

    def get_model_labels(self) -> List[str]:
        return sorted({e.model_label for e in self.get_endpoint_info() if e.model_label})

    def get_endpoint_urls(self) -> List[str]:
        """This replica's ROUTABLE endpoint URL view — what the state
        backend gossips to peer routers so the fleet hashes over one
        shared endpoint set even while discovery views momentarily
        diverge. Draining/warming/sleeping engines are excluded: a peer
        must never learn an endpoint it would have filtered locally."""
        return sorted(
            e.url for e in self.get_endpoint_info()
            if not getattr(e, "draining", False)
            and not getattr(e, "warming", False)
            and not getattr(e, "sleep", False)
        )

    async def initialize_client_sessions(
        self,
        prefill_model_labels: Optional[List[str]],
        decode_model_labels: Optional[List[str]],
    ) -> None:
        """Open long-lived sessions to the prefill/decode endpoints (disagg P/D)."""
        if not prefill_model_labels or not decode_model_labels or self.app is None:
            return
        for info in self.get_endpoint_info():
            if info.model_label in prefill_model_labels:
                self.app["prefill_client"] = aiohttp.ClientSession(
                    base_url=info.url, timeout=aiohttp.ClientTimeout(total=None)
                )
            elif info.model_label in decode_model_labels:
                self.app["decode_client"] = aiohttp.ClientSession(
                    base_url=info.url, timeout=aiohttp.ClientTimeout(total=None)
                )


class StaticServiceDiscovery(ServiceDiscovery):
    """Fixed backend list given on the CLI, with optional active health checks.

    Parity: reference ``service_discovery.py:206-341``. Health checking is
    an asyncio task issuing real test payloads per model type
    (cf. reference ``utils.py:162-174``).
    """

    def __init__(
        self,
        app=None,
        urls: Optional[List[str]] = None,
        models: Optional[List[str]] = None,
        aliases: Optional[Dict[str, str]] = None,
        model_labels: Optional[List[str]] = None,
        model_types: Optional[List[str]] = None,
        static_backend_health_checks: bool = False,
        prefill_model_labels: Optional[List[str]] = None,
        decode_model_labels: Optional[List[str]] = None,
        health_check_interval: float = 60.0,
        pools: Optional[List[str]] = None,
    ):
        urls = urls or []
        models = models or []
        if len(urls) != len(models):
            raise ValueError("static urls and models must have the same length")
        if pools and len(pools) != len(urls):
            raise ValueError("static pools and urls must have the same length")
        self.app = app
        self.urls = urls
        self.models = models
        self.aliases = aliases or {}
        self.model_labels = model_labels
        self.model_types = model_types
        self.pools = pools
        # pstlint: owned-by=task:__init__
        self.engine_ids = [str(uuid.uuid4()) for _ in urls]
        self.added_timestamp = time.time()
        self.enable_health_checks = static_backend_health_checks
        self.health_check_interval = health_check_interval
        self.prefill_model_labels = prefill_model_labels
        self.decode_model_labels = decode_model_labels
        # pstlint: owned-by=task:_health_loop
        self._unhealthy: set = set()
        # Consecutive failed health cycles per URL: routing-state eviction
        # (trie/pins/canary) waits for a SECOND consecutive failure — one
        # transient probe blip only unroutes the engine for a cycle and
        # must not wipe its warm-prefix knowledge.
        # pstlint: owned-by=task:_health_loop
        self._unhealthy_streaks: Dict[str, int] = {}
        # pstlint: owned-by=task:_health_loop,check_backend,_drain_reconcile_loop,set_draining
        self._draining: set = set()  # urls reporting is_draining
        # pstlint: owned-by=task:_health_loop,check_backend,_drain_reconcile_loop,set_warming
        self._warming: set = set()  # urls whose /ready reports warming
        # pstlint: owned-by=task:set_sleeping
        self._sleeping: set = set()  # urls slept via the router fan-out
        self._task: Optional[asyncio.Task] = None

    @staticmethod
    def _endpoint_hash(url: str, model: str) -> str:
        return hashlib.md5(f"{url}{model}".encode()).hexdigest()

    async def _probe(self, session: aiohttp.ClientSession, url: str, model: str, model_type: str) -> bool:
        try:
            mt = ModelType[model_type]
            payload = dict(ModelType.get_test_payload(model_type))
            payload["model"] = model
            async with session.post(
                url + mt.value, json=payload, timeout=aiohttp.ClientTimeout(total=10)
            ) as resp:
                return resp.status == 200
        except Exception as e:  # noqa: BLE001 — any failure means unhealthy
            logger.debug("health probe failed for %s (%s): %s", url, model, e)
            return False

    async def _probe_draining(
        self, session: aiohttp.ClientSession, url: str
    ) -> Optional[bool]:
        """None means the probe itself failed (timeout / connect error) —
        the caller keeps the last-known drain state rather than clearing a
        router-initiated drain on a transient blip."""
        try:
            async with session.get(
                url + "/is_draining", timeout=aiohttp.ClientTimeout(total=5)
            ) as resp:
                if resp.status == 200:
                    return bool((await resp.json()).get("is_draining", False))
                return False  # endpoint absent = not draining
        except Exception:  # noqa: BLE001
            return None

    async def _probe_warming(
        self, session: aiohttp.ClientSession, url: str
    ) -> Optional[bool]:
        """Shared /ready probe; tri-state like the drain probe."""
        return await probe_warming(session, url)

    @staticmethod
    def _feed_breaker(url: str, ok: bool) -> None:
        """Health probe outcomes feed the per-backend circuit breakers, so
        an engine that dies between requests trips its breaker (and a
        recovered one closes it) without waiting for live traffic."""
        from ..resilience import get_breaker_registry

        registry = get_breaker_registry()
        if registry is None:
            return
        if ok:
            registry.record_success(url)
        else:
            registry.record_failure(url)

    async def _health_loop(self) -> None:
        if not self.model_types or len(self.model_types) != len(self.urls):
            logger.error(
                "static health checks need one --static-model-types entry per "
                "backend; skipping health checking"
            )
            return
        logger.info(
            "static health loop started: %d backends, every %.1fs",
            len(self.urls), self.health_check_interval,
        )
        async def check_backend(session, url, model, mtype) -> Optional[tuple]:
            """One backend's probe pass; returns (endpoint hash, url) when
            unhealthy. _draining is mutated per URL (never
            snapshot-replaced): set_draining() may mark an engine
            mid-cycle, and an end-of-cycle overwrite would erase that mark
            until the next probe — up to a full interval of traffic to a
            draining engine."""
            drain_state = await self._probe_draining(session, url)
            if drain_state is True:
                self._draining.add(url)
            elif drain_state is False:
                self._draining.discard(url)
            # None: probe failed — keep last-known drain state.
            if url in self._draining:
                # Draining is deliberate, not a failure: the endpoint is
                # unroutable but its breaker is left alone.
                return None
            warm_state = await self._probe_warming(session, url)
            if warm_state is True:
                self._warming.add(url)
            elif warm_state is False:
                self._warming.discard(url)
            if url in self._warming:
                # Warming is deliberate too: skip the generation probe (it
                # would queue behind the compile pass, time out, and feed
                # the breaker a spurious failure) — the endpoint is simply
                # unroutable until /ready flips.
                return None
            ok = await self._probe(session, url, model, mtype)
            self._feed_breaker(url, ok)
            if not ok:
                logger.warning("%s at %s failed health check", model, url)
                return self._endpoint_hash(url, model), url
            return None

        async with aiohttp.ClientSession() as session:
            while True:
                try:
                    # Concurrent per backend: serial probes would let one
                    # black-holed engine (15s of timeouts) stall detection
                    # for every other backend in the cycle.
                    results = await asyncio.gather(*(
                        check_backend(session, url, model, mtype)
                        for url, model, mtype in zip(
                            self.urls, self.models, self.model_types
                        )
                    ))
                    hits = [r for r in results if r is not None]
                    self._unhealthy = {h for h, _ in hits}
                    # Routing-state eviction (the fleet-routing churn
                    # contract: trie/pins/canary dropped in one step) on
                    # the SECOND consecutive failed cycle: an engine that
                    # really left stays failed, while a single probe blip
                    # only unroutes it for one cycle — its warm-prefix
                    # knowledge survives the recovery.
                    from .routing.logic import evict_routing_endpoint

                    failed_urls = {url for _, url in hits}
                    for url in list(self._unhealthy_streaks):
                        if url not in failed_urls:
                            self._unhealthy_streaks.pop(url)
                    for url in failed_urls:
                        streak = self._unhealthy_streaks.get(url, 0) + 1
                        self._unhealthy_streaks[url] = streak
                        if streak == 2:
                            evict_routing_endpoint(url)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — one bad cycle must
                    # not silently kill health checking for good.
                    logger.error("health loop cycle failed: %s", e)
                await asyncio.sleep(self.health_check_interval)

    async def _drain_reconcile_loop(self) -> None:
        """Runs only when the full health loop is off: re-probe engines the
        router has marked draining (via the /drain fan-out or a tagged
        drain 503) or warming (via set_warming) so one that undrains,
        restarts, or finishes precompiling behind the router's back
        becomes routable again without an operator /undrain. Only marked
        engines are probed — the loop is idle while nothing drains."""
        async with aiohttp.ClientSession() as session:
            while True:
                await asyncio.sleep(self.health_check_interval)
                try:
                    for url in list(self._draining):
                        if await self._probe_draining(session, url) is False:
                            logger.info("engine %s no longer draining", url)
                            self._draining.discard(url)
                    for url in list(self._warming):
                        if await self._probe_warming(session, url) is False:
                            logger.info("engine %s finished warming", url)
                            self._warming.discard(url)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — keep reconciling
                    logger.error("drain reconcile cycle failed: %s", e)

    async def start(self) -> None:
        if self._task is None:
            self._task = spawn_owned(
                self._health_loop() if self.enable_health_checks
                else self._drain_reconcile_loop(),
                name="discovery-static-health",
            )
        await self.initialize_client_sessions(
            self.prefill_model_labels, self.decode_model_labels
        )

    def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def set_draining(self, url: str, draining: bool) -> None:
        if draining:
            self._draining.add(url)
        else:
            self._draining.discard(url)

    def set_warming(self, url: str, warming: bool) -> None:
        if warming:
            self._warming.add(url)
        else:
            self._warming.discard(url)

    def set_sleeping(self, url: str, sleeping: bool) -> None:
        if sleeping:
            self._sleeping.add(url)
        else:
            self._sleeping.discard(url)

    def get_endpoint_info(self) -> List[EndpointInfo]:
        infos = []
        for i, (url, model) in enumerate(zip(self.urls, self.models)):
            if self._endpoint_hash(url, model) in self._unhealthy:
                continue
            label = self.model_labels[i] if self.model_labels else "default"
            infos.append(
                EndpointInfo(
                    url=url,
                    model_names=[model],
                    Id=self.engine_ids[i],
                    added_timestamp=self.added_timestamp,
                    model_label=label,
                    sleep=url in self._sleeping,
                    draining=url in self._draining,
                    warming=url in self._warming,
                    pool=(self.pools[i] if self.pools else "fused"),
                    model_info={model: ModelInfo(id=model)},
                )
            )
        return infos


class _K8sWatcherBase(ServiceDiscovery):
    """Shared machinery for the two Kubernetes discovery modes."""

    def __init__(
        self,
        app=None,
        namespace: str = "default",
        port: int = 8000,
        label_selector: Optional[str] = None,
        prefill_model_labels: Optional[List[str]] = None,
        decode_model_labels: Optional[List[str]] = None,
    ):
        from .k8s_client import K8sClient  # local import: optional subsystem

        self.app = app
        self.namespace = namespace
        self.port = port
        self.label_selector = label_selector
        self.prefill_model_labels = prefill_model_labels
        self.decode_model_labels = decode_model_labels
        self.k8s = K8sClient()
        # Mutations hold the watcher's asyncio lock; the lock-order check
        # additionally forbids awaits inside those regions (fetches are
        # materialized BEFORE the lock, hashtrie-walk style).
        # pstlint: owned-by=lock:_lock
        self.available_engines: Dict[str, EndpointInfo] = {}
        self._lock = asyncio.Lock()
        self._task: Optional[asyncio.Task] = None
        self._healthy = True

    def get_endpoint_info(self) -> List[EndpointInfo]:
        return list(self.available_engines.values())

    @staticmethod
    def _evict_breaker(url: str) -> None:
        """An engine left the fleet for good: drop its breaker, metric
        series, per-engine request-stat aggregates, AND its routing state
        (prefix trie, session pins, cached scores) in one step — churn
        must never leave a phantom engine as some prompt's deepest trie
        match or some session's pin."""
        from ..resilience import get_breaker_registry
        from .routing.logic import evict_routing_endpoint
        from .stats.request_stats import get_request_stats_monitor

        registry = get_breaker_registry()
        if registry is not None:
            registry.evict(url)
        try:
            get_request_stats_monitor().evict_url(url)
        except ValueError:
            pass  # monitor not initialized (unit-test harness)
        evict_routing_endpoint(url)

    def get_health(self) -> bool:
        return self._task is not None and not self._task.done()

    def set_draining(self, url: str, draining: bool) -> None:
        # No watch event fires for a router-initiated drain (the pod keeps
        # running), so flip the flag on the live EndpointInfo directly; the
        # next pod/service event re-fetches /is_draining and agrees.
        for info in self.available_engines.values():
            if info.url == url:
                info.draining = draining

    def set_warming(self, url: str, warming: bool) -> None:
        for info in self.available_engines.values():
            if info.url == url:
                info.warming = warming

    def set_sleeping(self, url: str, sleeping: bool) -> None:
        for info in self.available_engines.values():
            if info.url == url:
                info.sleep = sleeping

    async def start(self) -> None:
        if self._task is None:
            self._task = spawn_owned(self._watch_loop(), name="discovery-k8s-watch")
        await self.initialize_client_sessions(
            self.prefill_model_labels, self.decode_model_labels
        )

    def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _fetch_models(self, base_url: str) -> Dict[str, ModelInfo]:
        """Ask an engine which models (incl. LoRA adapters) it serves."""
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"{base_url}/v1/models", timeout=aiohttp.ClientTimeout(total=10)
            ) as resp:
                resp.raise_for_status()
                data = await resp.json()
        return {m["id"]: ModelInfo.from_dict(m) for m in data.get("data", [])}

    async def _fetch_flag(self, base_url: str, path: str, key: str) -> Optional[bool]:
        """None means the probe itself failed (timeout / connect error);
        a non-200 means the endpoint is absent → flag off."""
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(
                    f"{base_url}{path}", timeout=aiohttp.ClientTimeout(total=5)
                ) as resp:
                    if resp.status == 200:
                        return bool((await resp.json()).get(key, False))
                    return False
        except Exception:  # noqa: BLE001
            return None

    async def _fetch_sleep_status(self, base_url: str) -> bool:
        return bool(await self._fetch_flag(base_url, "/is_sleeping", "is_sleeping"))

    async def _fetch_drain_status(self, base_url: str, last_known: bool = False) -> bool:
        """A failed /is_draining probe keeps the last-known drain state
        (same tri-state rule as StaticServiceDiscovery._probe_draining):
        collapsing probe failure to False would flap a draining engine
        back to routable on any watch-event refetch that times out."""
        flag = await self._fetch_flag(base_url, "/is_draining", "is_draining")
        return last_known if flag is None else flag

    async def _fetch_warming_status(
        self, base_url: str, last_known: bool = False
    ) -> bool:
        """Warming from the engine's /ready (shared ``probe_warming``). A
        failed probe keeps the last-known state — flapping a warming
        engine to routable on one timed-out refetch would feed its
        compile storm live traffic."""
        async with aiohttp.ClientSession() as session:
            flag = await probe_warming(session, base_url)
        return last_known if flag is None else flag

    async def _watch_loop(self) -> None:
        raise NotImplementedError


class K8sPodIPServiceDiscovery(_K8sWatcherBase):
    """Watch engine pods and address them by pod IP.

    Parity: reference ``service_discovery.py:344-746`` (_watch_engines
    :571-617, _on_engine_update :657-696). Pods are eligible once Ready;
    terminating/not-ready pods are removed; each added pod is queried for
    its model list and sleep state.
    """

    async def _watch_loop(self) -> None:
        while True:
            try:
                async for event in self.k8s.watch_pods(
                    self.namespace, self.label_selector
                ):
                    await self._on_pod_event(event)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — keep watching
                logger.error("pod watch error (retrying in 0.5s): %s", e)
                await asyncio.sleep(0.5)

    @staticmethod
    def _pod_ready(pod: dict) -> bool:
        status = pod.get("status", {})
        if status.get("phase") != "Running":
            return False
        for cond in status.get("conditions", []) or []:
            if cond.get("type") == "Ready":
                return cond.get("status") == "True"
        return False

    async def _on_pod_event(self, event: dict) -> None:
        etype = event.get("type")
        pod = event.get("object", {})
        meta = pod.get("metadata", {})
        name = meta.get("name", "")
        ip = pod.get("status", {}).get("podIP")
        deleting = meta.get("deletionTimestamp") is not None
        if etype == "DELETED" or deleting or not self._pod_ready(pod) or not ip:
            async with self._lock:
                removed = self.available_engines.pop(name, None)
            if removed is not None:
                logger.info("engine %s removed from pool", name)
                self._evict_breaker(removed.url)
            return
        url = f"http://{ip}:{self.port}"
        try:
            model_info = await self._fetch_models(url)
        except Exception as e:  # noqa: BLE001
            logger.debug("engine %s not serving /v1/models yet: %s", name, e)
            return
        prev = self.available_engines.get(name)
        sleep, draining, warming = await asyncio.gather(
            self._fetch_sleep_status(url),
            self._fetch_drain_status(url, prev.draining if prev else False),
            self._fetch_warming_status(url, prev.warming if prev else False),
        )
        labels = meta.get("labels", {}) or {}
        info = EndpointInfo(
            url=url,
            model_names=list(model_info),
            Id=meta.get("uid", name),
            added_timestamp=time.time(),
            model_label=labels.get("model", labels.get("app", "default")),
            sleep=sleep,
            draining=draining,
            warming=warming,
            pool=_pool_label(labels),
            pod_name=name,
            namespace=self.namespace,
            model_info=model_info,
        )
        async with self._lock:
            self.available_engines[name] = info
        logger.info("engine %s added: %s models=%s", name, url, info.model_names)


class K8sServiceNameServiceDiscovery(_K8sWatcherBase):
    """Watch Services and address engines by cluster-DNS service name.

    Parity: reference ``service_discovery.py:749-1150``.
    """

    async def _watch_loop(self) -> None:
        while True:
            try:
                async for event in self.k8s.watch_services(
                    self.namespace, self.label_selector
                ):
                    await self._on_service_event(event)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                logger.error("service watch error (retrying in 0.5s): %s", e)
                await asyncio.sleep(0.5)

    async def _on_service_event(self, event: dict) -> None:
        etype = event.get("type")
        svc = event.get("object", {})
        meta = svc.get("metadata", {})
        name = meta.get("name", "")
        if etype == "DELETED":
            async with self._lock:
                removed = self.available_engines.pop(name, None)
            if removed is not None:
                self._evict_breaker(removed.url)
            return
        url = f"http://{name}.{self.namespace}.svc.cluster.local:{self.port}"
        try:
            model_info = await self._fetch_models(url)
        except Exception as e:  # noqa: BLE001
            logger.debug("service %s not ready: %s", name, e)
            return
        prev = self.available_engines.get(name)
        sleep, draining, warming = await asyncio.gather(
            self._fetch_sleep_status(url),
            self._fetch_drain_status(url, prev.draining if prev else False),
            self._fetch_warming_status(url, prev.warming if prev else False),
        )
        labels = meta.get("labels", {}) or {}
        info = EndpointInfo(
            url=url,
            model_names=list(model_info),
            Id=meta.get("uid", name),
            added_timestamp=time.time(),
            model_label=labels.get("model", labels.get("app", "default")),
            sleep=sleep,
            draining=draining,
            warming=warming,
            pool=_pool_label(labels),
            service_name=name,
            namespace=self.namespace,
            model_info=model_info,
        )
        async with self._lock:
            self.available_engines[name] = info


# App-scoped lifecycle (docs/router-ha.md, app-scope pstlint check): the
# discovery instance lives in the current app scope — the aiohttp app
# itself when the app factory bound it, an implicit per-context scope for
# bare callers (unit tests). Two router apps in one process each resolve
# their OWN discovery; there is no last-app-wins module global left to
# bleed through.
_SCOPE_KEY = "service_discovery"


def _create(sd_type: ServiceDiscoveryType, *args, **kwargs) -> ServiceDiscovery:
    if sd_type == ServiceDiscoveryType.STATIC:
        return StaticServiceDiscovery(*args, **kwargs)
    if sd_type == ServiceDiscoveryType.K8S:
        mode = (kwargs.pop("k8s_service_discovery_type", None) or "pod-ip").strip().lower()
        if mode == "service-name":
            return K8sServiceNameServiceDiscovery(*args, **kwargs)
        return K8sPodIPServiceDiscovery(*args, **kwargs)
    raise ValueError(f"invalid service discovery type {sd_type}")


def initialize_service_discovery(sd_type: ServiceDiscoveryType, *args, **kwargs) -> ServiceDiscovery:
    """Create (or replace) the current scope's discovery instance.

    Replacement instead of a hard error: the app factory owns the
    lifecycle, and unit tests re-initialize freely. A previous instance
    in the SAME scope is closed so its watch/health tasks do not leak;
    another app's instance (a different scope) is untouched."""
    from . import appscope

    prev = appscope.scoped_get(_SCOPE_KEY)
    if prev is not None:
        logger.warning(
            "service discovery re-initialized; replacing the previous instance"
        )
        prev.close()
    return appscope.scoped_set(_SCOPE_KEY, _create(sd_type, *args, **kwargs))


def reconfigure_service_discovery(sd_type: ServiceDiscoveryType, *args, **kwargs) -> ServiceDiscovery:
    from . import appscope

    old = appscope.scoped_get(_SCOPE_KEY)
    if old is None:
        raise ValueError("service discovery not initialized")
    new = _create(sd_type, *args, **kwargs)
    old.close()
    return appscope.scoped_set(_SCOPE_KEY, new)


def get_service_discovery() -> ServiceDiscovery:
    from . import appscope

    sd = appscope.scoped_get(_SCOPE_KEY)
    if sd is None:
        raise ValueError("service discovery not initialized")
    return sd


def teardown_service_discovery() -> None:
    from . import appscope

    sd = appscope.scoped_get(_SCOPE_KEY)
    if sd is not None:
        sd.close()
        appscope.scoped_set(_SCOPE_KEY, None)
