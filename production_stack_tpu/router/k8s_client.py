"""Minimal in-cluster Kubernetes API client (no external dependency).

The reference router depends on the official ``kubernetes`` Python client
for pod/service watches (``service_discovery.py:571-617``). Here the same
capability is provided natively: service-account credentials from the
standard in-cluster mount, aiohttp for the HTTP layer, and the K8s
``watch=true`` chunked-JSON stream protocol.
"""

# pstlint: disable-file=hop-contract(Kubernetes API list/watch calls are not engine hops; the deadline/trace propagation contract does not apply to the apiserver)
from __future__ import annotations

import json
import os
import ssl
from typing import AsyncIterator, Optional

import aiohttp

from ..logging_utils import init_logger

logger = init_logger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class K8sClient:
    """Talks to the API server from inside a pod (or via env overrides).

    Env overrides for out-of-cluster testing:
      PST_K8S_API_SERVER  (e.g. http://127.0.0.1:8001 — a kubectl proxy)
      PST_K8S_TOKEN / PST_K8S_CA_CERT
    """

    def __init__(self) -> None:
        self.api_server = os.environ.get("PST_K8S_API_SERVER")
        if not self.api_server:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if host:
                self.api_server = f"https://{host}:{port}"
        self.token = os.environ.get("PST_K8S_TOKEN")
        if not self.token and os.path.exists(f"{SA_DIR}/token"):
            with open(f"{SA_DIR}/token") as f:
                self.token = f.read().strip()
        ca = os.environ.get("PST_K8S_CA_CERT", f"{SA_DIR}/ca.crt")
        self.ssl_ctx: Optional[ssl.SSLContext] = None
        if self.api_server and self.api_server.startswith("https") and os.path.exists(ca):
            self.ssl_ctx = ssl.create_default_context(cafile=ca)

    def _headers(self) -> dict:
        h = {"Accept": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    async def _watch(
        self, resource: str, namespace: str, label_selector: Optional[str]
    ) -> AsyncIterator[dict]:
        """Yield watch events for a namespaced resource, forever-per-call.

        First lists the resource (synthesizing ADDED events) so callers
        converge even if they start after the pods, then opens the watch
        stream from the list's resourceVersion.
        """
        if not self.api_server:
            raise RuntimeError(
                "no Kubernetes API server configured (not in-cluster and "
                "PST_K8S_API_SERVER unset)"
            )
        base = f"{self.api_server}/api/v1/namespaces/{namespace}/{resource}"
        params = {}
        if label_selector:
            params["labelSelector"] = label_selector
        timeout = aiohttp.ClientTimeout(total=None, sock_read=None)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            async with session.get(
                base, params=params, headers=self._headers(), ssl=self.ssl_ctx
            ) as resp:
                resp.raise_for_status()
                listing = await resp.json()
            for item in listing.get("items", []):
                yield {"type": "ADDED", "object": item}
            rv = listing.get("metadata", {}).get("resourceVersion", "0")
            wparams = dict(params, watch="true", resourceVersion=rv)
            async with session.get(
                base, params=wparams, headers=self._headers(), ssl=self.ssl_ctx
            ) as resp:
                resp.raise_for_status()
                buf = b""
                async for chunk in resp.content.iter_any():
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        if not line.strip():
                            continue
                        try:
                            yield json.loads(line)
                        except json.JSONDecodeError:
                            logger.debug("skipping malformed watch line")

    def watch_pods(
        self, namespace: str, label_selector: Optional[str] = None
    ) -> AsyncIterator[dict]:
        return self._watch("pods", namespace, label_selector)

    def watch_services(
        self, namespace: str, label_selector: Optional[str] = None
    ) -> AsyncIterator[dict]:
        return self._watch("services", namespace, label_selector)
