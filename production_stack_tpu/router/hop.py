"""The sanctioned builder for outbound hop headers.

Every HTTP request the router sends toward an engine (or any service
participating in a request's story — the KV controller, a disagg prefill
leg, an admin fan-out) must carry the propagation trio from PRs 2-3:

- ``X-Request-Id`` — the log/timeline/stats join key;
- ``traceparent`` — the W3C trace context, naming the current span as
  parent so retries/hedges/resume legs render as one tree;
- ``X-PST-Deadline-Ms`` — the *remaining* budget, recomputed per attempt.

:func:`hop_headers` is the one place that knows how to assemble them;
the ``hop-contract`` pstlint check (docs/static-analysis.md) flags any
outbound session call in ``router/`` whose ``headers=`` does not derive
from it (or from ``request_service._trace_headers``, its span-aware
wrapper). Control-plane traffic with no request context (canary probes,
metric scrapes, discovery probes, k8s watches) is exempted by file-level
suppressions at its call sites, with reasons.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

from ..obs import REQUEST_ID_HEADER, TRACEPARENT_HEADER
from ..resilience.deadline import DEADLINE_HEADER, Deadline, with_deadline_header


def hop_headers(
    base: Optional[Mapping[str, str]] = None,
    *,
    request_id: Optional[str] = None,
    span=None,
    deadline: Optional[Deadline] = None,
    from_headers: Optional[Mapping[str, str]] = None,
) -> Dict[str, str]:
    """Assemble outbound hop headers.

    ``base`` seeds the result (e.g. forwardable client headers).
    ``from_headers`` copies whichever of the trio an inbound mapping
    already carries — the relay form, for hops that forward someone
    else's context (KV-controller lookups during routing). Explicit
    ``request_id``/``span``/``deadline`` win over both: they describe
    *this* hop (the span becomes the parent, the deadline re-shrinks).
    """
    headers: Dict[str, str] = dict(base) if base else {}
    if from_headers is not None:
        # The full trio relays — including the (as-of-receipt) remaining
        # budget, so a relay hop can shed an already-expired request. An
        # explicit deadline= below re-shrinks it for this hop.
        for name in (REQUEST_ID_HEADER, TRACEPARENT_HEADER, DEADLINE_HEADER):
            value = from_headers.get(name)
            if value is not None:
                headers.setdefault(name, value)
    if request_id:
        headers[REQUEST_ID_HEADER] = request_id
    if span is not None:
        traceparent = span.traceparent()
        if traceparent:
            headers[TRACEPARENT_HEADER] = traceparent
    if deadline is not None:
        headers = with_deadline_header(headers, deadline)
    return headers
