"""Fleet-routing scoring: one policy over affinity × headroom × health.

The score the :class:`~.logic.FleetRouter` maximizes per routing decision:

    score(e) = (COLD_BASE_TOKENS + expected_hit_tokens(e))
               × kv_headroom(e) × canary_health(e)

- ``expected_hit_tokens`` comes from the LOCAL prefix hashtrie first
  (``HashTrie.match_depths`` — zero extra hops on the hot path) with the
  kvserver ``/lookup`` consulted only when the prompt is above the
  kvaware token threshold AND the trie cannot already prove a hit that
  big (:class:`KvLookupClient`). Below the threshold routing NEVER
  touches the network — asserted by a test that routes with the kvserver
  unreachable.
- ``kv_headroom`` is ``1 − pst_engine_kv_page_occupancy`` from the
  engine-stats scrape snapshot (floored, never zeroed: an engine at 100%
  occupancy is strongly demoted but the argmax stays defined when the
  whole fleet is full).
- ``canary_health`` compares the engine's last canary TTFT against the
  fleet's best (an engine twice as slow as the best scores half); engines
  without a probe yet score 1.0 — innocent until probed.

Both headroom and health read the already-running scrape/canary
snapshots: scoring adds **no new blocking I/O per request**.

Loads for the bounded-load constraint come from the FLEET-MERGED
request-stats view (``get_request_stats(fleet=True)``): each replica's
own routed in-flight counts ride the ``request_stats`` gossip digest and
merge additively — one provider, one merge, no double counting — so
every replica sheds a hot-spotted engine the same way.
"""

from __future__ import annotations

import random
from collections import OrderedDict
from typing import Any, Dict, Optional, Sequence

import aiohttp

from ...logging_utils import init_logger
from ..hop import hop_headers

logger = init_logger(__name__)

# Chars-per-token estimate for the char-chunked trie's hit depths (the
# trie stores text chunks, the score speaks tokens).
CHARS_PER_TOKEN = 4.0
# Baseline "cold" token mass: engines with zero cached prefix still
# differentiate on headroom × health instead of all scoring 0.
COLD_BASE_TOKENS = 64.0
# Headroom floor — demote, never annihilate (see module docstring).
MIN_HEADROOM = 0.05
# Health floor: one terrible canary sample must not erase a huge cached
# prefix entirely.
MIN_HEALTH = 0.05


def kv_headroom(engine_stats: Optional[Any]) -> float:
    """Free KV fraction from a scraped :class:`EngineStats` snapshot."""
    occ = 0.0
    if engine_stats is not None:
        occ = float(getattr(engine_stats, "engine_kv_page_occupancy", 0.0))
        if occ <= 0.0:
            # Engines predating pst_engine_kv_page_occupancy still export
            # the vllm-compatible usage gauge.
            occ = float(getattr(engine_stats, "gpu_cache_usage_perc", 0.0))
    return max(1.0 - min(max(occ, 0.0), 1.0), MIN_HEADROOM)


def canary_health(
    url: str, canary_ttfts: Dict[str, float]
) -> float:
    """Relative canary-TTFT health in (0, 1]; 1.0 when unprobed."""
    ttft = canary_ttfts.get(url, 0.0)
    if ttft <= 0.0:
        return 1.0
    best = min((t for t in canary_ttfts.values() if t > 0.0), default=0.0)
    if best <= 0.0:
        return 1.0
    return max(min(best / ttft, 1.0), MIN_HEALTH)


def compute_availability(engine_stats: Optional[Any]) -> float:
    """Prefill-pool scoring input (docs/disagg.md): free compute,
    approximated by the engine's running+queued depth — prefill is
    compute-bound, so queue depth predicts its TTFT where KV headroom
    says almost nothing. In (0, 1]; 1.0 = idle."""
    if engine_stats is None:
        return 1.0
    depth = float(
        getattr(engine_stats, "num_running_requests", 0) or 0
    ) + float(getattr(engine_stats, "num_queuing_requests", 0) or 0)
    return 1.0 / (1.0 + max(depth, 0.0) / 4.0)


def score_engines(
    urls: Sequence[str],
    hit_tokens: Dict[str, float],
    engine_stats: Dict[str, Any],
    canary_ttfts: Dict[str, float],
    pool: Optional[str] = None,
) -> Dict[str, float]:
    """The fused score per candidate engine (see module docstring).

    ``pool`` specializes the capacity factor for disagg legs
    (docs/disagg.md): the prefill pool is compute-bound, so queue/compute
    availability replaces KV headroom; the decode pool is
    bandwidth/KV-bound, so the standard headroom factor applies. Fused
    engines score under whichever leg is being routed — they stay
    eligible for both, which is what lets mixed fleets degrade
    gracefully."""

    def capacity(url: str) -> float:
        es = engine_stats.get(url)
        if pool == "prefill":
            return max(compute_availability(es), MIN_HEADROOM)
        return kv_headroom(es)

    return {
        url: (
            (COLD_BASE_TOKENS + max(hit_tokens.get(url, 0.0), 0.0))
            * capacity(url)
            * canary_health(url, canary_ttfts)
        )
        for url in urls
    }


def load_bound(loads: Dict[str, float], urls: Sequence[str],
               factor: float) -> float:
    """Bounded-load limit: ``c × max(mean load, 1)`` — the same rule as
    ``ConsistentHashRing.get_node_bounded``, so the argmax spill and the
    session-ring spill shed a hot engine at the same threshold."""
    if not urls:
        return factor
    mean = sum(loads.get(u, 0.0) for u in urls) / len(urls)
    return factor * max(mean, 1.0)


def pick_bounded(
    scores: Dict[str, float],
    loads: Dict[str, float],
    bound: float,
    batch_tier: bool = False,
) -> tuple:
    """Argmax over scores subject to the bounded-load constraint.

    Returns ``(url, spill_reason)`` where spill_reason is ``None`` (best
    scorer picked), ``"load"`` (best was over the limit, spilled to the
    next-best under it), or ``"saturated"`` (every candidate over the
    limit — fail open to the best scorer; starving the whole fleet would
    be worse than the hot spot).

    ``batch_tier`` (docs/multi-tenancy.md): batch-class work may never
    pin itself past the bounded-load rule — on saturation it takes the
    LEAST-LOADED candidate instead of the best scorer, so a batch flood
    spreads across the fleet's slack rather than piling affinity-first
    onto the engine interactive traffic is hot on.

    Exact score ties (a cold fleet: no cached prefixes, equal headroom,
    no canary samples) break by lowest load, then RANDOMLY — a
    lexicographic tiebreak would funnel every cold prompt onto one
    engine and the trie would then cement each prefix there, the exact
    hot-spotting this policy exists to prevent. Randomness only decides
    between engines the score genuinely cannot distinguish, so replica
    determinism is lost only where there is no affinity to protect.
    """
    order = sorted(
        scores,
        key=lambda u: (-scores[u], loads.get(u, 0.0), random.random()),
    )
    best = order[0]
    for url in order:
        if loads.get(url, 0.0) < bound:
            return url, (None if url == best else "load")
    if batch_tier:
        coldest = min(order, key=lambda u: loads.get(u, 0.0))
        return coldest, "saturated"
    return best, "saturated"


def fleet_loads(
    urls: Sequence[str],
    request_stats: Dict[str, Any],
) -> Dict[str, float]:
    """Per-engine routed-in-flight load, fleet-wide.

    ``request_stats`` is the FLEET-MERGED request-stats view
    (``get_request_stats(fleet=True)`` — under a shared state backend the
    monitor already adds live peers' gossiped ``in_prefill``/
    ``in_decoding`` counts, each replica contributing exactly its own
    traffic). The in-flight counts ride ONE pipeline: the request-stats
    digest. The separate ``endpoint_loads`` gossip key this function used
    to merge carried the same numbers twice and is gone
    (docs/router-ha.md).
    """
    loads: Dict[str, float] = {}
    for url in urls:
        rs = request_stats.get(url)
        loads[url] = float(
            getattr(rs, "in_prefill_requests", 0)
            + getattr(rs, "in_decoding_requests", 0)
        ) if rs is not None else 0.0
    return loads


class KvLookupClient:
    """The kvserver ``/lookup`` leg of scoring (above-threshold only).

    One long-lived ClientSession (hot-path connection reuse, same
    rationale as ``KvawareRouter``), short timeout, and the request's
    id/trace context relayed on the hop so a slow controller shows up in
    that request's timeline instead of as unattributed routing latency.
    """

    def __init__(self, controller_url: str, timeout: float = 2.0,
                 tokenizer_name: Optional[str] = None) -> None:
        self.controller_url = controller_url.rstrip("/")
        self.timeout = timeout
        self.tokenizer_name = tokenizer_name
        self._tokenizer = None
        self._session: Optional[aiohttp.ClientSession] = None

    def _get_tokenizer(self, model: str):
        if self._tokenizer is None:
            from ...engine.tokenizer import get_tokenizer

            self._tokenizer = get_tokenizer(self.tokenizer_name or model)
        return self._tokenizer

    def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout)
            )
        return self._session

    async def aclose(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
        self._session = None

    async def lookup(
        self, model: str, text: str,
        headers: Optional[Dict[str, str]] = None,
    ) -> Dict[str, float]:
        """url → matched token count from the controller; raises on any
        failure (the caller degrades to the local estimate)."""
        from ...kvcache.hashing import chunk_hashes

        token_ids = self._get_tokenizer(model).encode(text)
        hashes = chunk_hashes(token_ids)
        if not hashes:
            return {}
        session = self._get_session()
        async with session.post(
            f"{self.controller_url}/lookup",
            json={"model": model, "hashes": hashes},
            headers=hop_headers(from_headers=headers or {}),
        ) as resp:
            resp.raise_for_status()
            data = await resp.json()
        return {
            k: float(v) for k, v in (data.get("matches") or {}).items()
        }


class SessionPins:
    """Bounded session → engine pin table (LRU on every re-pin, so a
    long-lived active session is never evicted before idle newcomers).

    Tenant-class aware (docs/multi-tenancy.md): each pin records the
    tier that created it, and capacity eviction pops **batch-tier pins
    first** (LRU within the tier) — a batch flood churning thousands of
    fresh session ids can evict only its own class's pins, never an
    interactive tenant's warm affinity."""

    def __init__(self, max_pins: int = 8192) -> None:
        self.max_pins = max_pins
        # pstlint: owned-by=task:pin,drop_endpoint
        self._pins: "OrderedDict[str, tuple]" = OrderedDict()

    def get(self, session_id: str) -> Optional[str]:
        entry = self._pins.get(session_id)
        return entry[0] if entry is not None else None

    def pin(self, session_id: str, url: str, batch_tier: bool = False) -> None:
        prev = self._pins.get(session_id)
        if prev is not None and not prev[1]:
            # A pin's tier never downgrades: one batch-stamped request on
            # an interactive session (e.g. a batch line reusing its id)
            # must not make the session's warm affinity first-evicted.
            batch_tier = False
        self._pins[session_id] = (url, bool(batch_tier))
        self._pins.move_to_end(session_id)
        while len(self._pins) > self.max_pins:
            victim = None
            for sid, (_, is_batch) in self._pins.items():  # LRU order
                if is_batch:
                    victim = sid
                    break
            if victim is None:  # no batch pin left: evict plain LRU
                self._pins.popitem(last=False)
            else:
                self._pins.pop(victim, None)

    def drop_endpoint(self, url: str) -> None:
        """An engine left the fleet: forget every pin to it in one step
        so the very next request per session remaps through the ring."""
        stale = [sid for sid, (u, _) in self._pins.items() if u == url]
        for sid in stale:
            self._pins.pop(sid, None)

    def __len__(self) -> int:
        return len(self._pins)
