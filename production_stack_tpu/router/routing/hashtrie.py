"""Chunked hash trie for prefix-aware routing.

Capability parity with the reference's ``src/vllm_router/prefix/hashtrie.py``
(chunked 128-char xxhash trie, per-node asyncio locks, insert :58-74,
longest_prefix_match :76-103). Additions over the reference: a node budget
with LRU pruning so a long-running router cannot grow without bound, and
endpoint eviction when discovery removes a backend.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional, Set, Tuple

import xxhash


class _Node:
    __slots__ = ("children", "endpoints", "lock", "last_access")

    def __init__(self) -> None:
        # Mutations require the owning node's asyncio lock (the
        # lock-discipline pstlint check enforces 'with <node>.lock').
        # pstlint: owned-by=lock:lock
        self.children: Dict[int, "_Node"] = {}
        # pstlint: owned-by=lock:lock
        self.endpoints: Set[str] = set()
        self.lock = asyncio.Lock()
        self.last_access = time.monotonic()


class HashTrie:
    def __init__(self, chunk_size: int = 128, max_nodes: int = 262144) -> None:
        self.chunk_size = chunk_size
        self.max_nodes = max_nodes
        self.root = _Node()
        self._node_count = 1

    def _chunks(self, text: str):
        for i in range(0, len(text), self.chunk_size):
            yield xxhash.xxh64_intdigest(text[i : i + self.chunk_size])

    def hash_path(self, text: str, max_chunks: int = 64) -> list:
        """The chunk-hash path for ``text`` (bounded) — the replication
        unit router replicas gossip instead of raw prompt text: peers can
        merge routing knowledge without ever exchanging prompt content."""
        out = []
        for h in self._chunks(text):
            out.append(h)
            if len(out) >= max_chunks:
                break
        return out

    async def insert(self, text: str, endpoint: str) -> None:
        """Record that ``endpoint`` has served (and likely cached) ``text``."""
        await self.insert_hashes(list(self._chunks(text)), endpoint)

    async def insert_hashes(self, hashes, endpoint: str) -> None:
        """Insert by precomputed chunk-hash path (local inserts and
        replicated inserts from peer routers share this walk)."""
        node = self.root
        for h in hashes:
            async with node.lock:
                node.endpoints.add(endpoint)
                child = node.children.get(h)
                if child is None:
                    if self._node_count >= self.max_nodes:
                        self._prune()
                    child = _Node()
                    node.children[h] = child
                    self._node_count += 1
            node = child
            node.last_access = time.monotonic()
        async with node.lock:
            node.endpoints.add(endpoint)

    async def longest_prefix_match(
        self, text: str, available: Optional[Set[str]] = None
    ) -> Tuple[int, Set[str]]:
        """Return (matched chars, endpoints at the deepest matched node).

        Only endpoints in ``available`` (if given) count as matches; the
        walk stops where no available endpoint remains on the path.
        """
        node = self.root
        matched_chars = 0
        best: Set[str] = set()
        text_len = len(text)
        for i, h in enumerate(self._chunks(text)):
            child = node.children.get(h)
            if child is None:
                break
            eps = child.endpoints if available is None else child.endpoints & available
            if not eps:
                break
            node = child
            node.last_access = time.monotonic()
            matched_chars = min((i + 1) * self.chunk_size, text_len)
            best = set(eps)
        return matched_chars, best

    async def match_depths(
        self,
        text: str,
        available: Optional[Set[str]] = None,
        max_chunks: int = 64,
    ) -> Dict[str, int]:
        """Per-endpoint matched depth (chars) along ``text``'s chunk path.

        Unlike :meth:`longest_prefix_match` (which only reports the
        deepest node's endpoint set), this returns how deep EVERY
        available endpoint matches — the per-engine expected-hit input
        fleet scoring multiplies against KV headroom and canary health.
        The walk stops where no available endpoint remains on the path,
        same rule as ``longest_prefix_match``; bounded at ``max_chunks``
        so scoring cost stays O(1) in prompt length.
        """
        node = self.root
        depths: Dict[str, int] = {}
        text_len = len(text)
        for i, h in enumerate(self._chunks(text)):
            if i >= max_chunks:
                break
            child = node.children.get(h)
            if child is None:
                break
            eps = (
                child.endpoints if available is None
                else child.endpoints & available
            )
            if not eps:
                break
            matched = min((i + 1) * self.chunk_size, text_len)
            for ep in eps:
                depths[ep] = matched
            node = child
            node.last_access = time.monotonic()
        return depths

    async def remove_endpoint(self, endpoint: str) -> None:
        """Drop a disappeared endpoint from the whole trie.

        Takes each node's lock for its own mutation (one lock held at a
        time, same discipline as insert) — an insert interleaving at the
        same node must never observe a half-applied discard."""

        async def walk(node: _Node) -> None:
            async with node.lock:
                node.endpoints.discard(endpoint)
                children = list(node.children.values())
            for child in children:
                await walk(child)

        await walk(self.root)

    def _prune(self) -> None:
        """Drop the least-recently-accessed top-level subtree (approx. LRU)."""
        if not self.root.children:
            return
        oldest = min(self.root.children, key=lambda h: self.root.children[h].last_access)

        def count(node: _Node) -> int:
            return 1 + sum(count(c) for c in node.children.values())

        removed = count(self.root.children[oldest])
        # pstlint: disable=lock-discipline(_prune runs synchronously — no awaits — from insert, which already holds the insertion node's lock; taking root.lock here would deadlock when that node IS root, and asyncio's single thread makes the subtree drop atomic as-is)
        del self.root.children[oldest]
        self._node_count -= removed
