"""Routing policies: roundrobin, session, kvaware, prefixaware, disagg P/D.

Capability parity with the reference's
``src/vllm_router/routers/routing_logic.py`` (policy enum :49-54,
RoundRobinRouter :126-166, SessionRouter :169-218, KvawareRouter :221-338,
PrefixAwareRouter :341-417, DisaggregatedPrefillRouter :420-460,
initialize/reconfigure/get :464-520).

Redesigns:
- The consistent-hash ring is implemented natively (xxhash + bisect, 160
  virtual nodes per endpoint) instead of depending on ``uhashring``.
- KV-aware routing queries the production-stack-tpu cache controller
  (:mod:`production_stack_tpu.kvserver.controller`) over HTTP with
  token-chunk hashes computed by the shared scheme in
  :mod:`production_stack_tpu.kvcache.hashing`, instead of ZMQ into LMCache.
- Prefix-aware routing breaks ties by live engine load instead of randomly.
"""

from __future__ import annotations

import bisect
import enum
import random
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional, Sequence, Tuple

import xxhash

from ...logging_utils import init_logger
from ..hop import hop_headers
from ...utils import SingletonABCMeta
from ..service_discovery import EndpointInfo
from .hashtrie import HashTrie

logger = init_logger(__name__)


class RoutingLogic(enum.Enum):
    ROUND_ROBIN = "roundrobin"
    SESSION_BASED = "session"
    KVAWARE = "kvaware"
    PREFIXAWARE = "prefixaware"
    DISAGGREGATED_PREFILL = "disaggregated_prefill"


def extract_prompt_text(request_json: Dict[str, Any]) -> str:
    """Flatten a chat/completion body into routing text (stable across calls)."""
    if "messages" in request_json:
        parts = []
        for message in request_json.get("messages") or []:
            content = message.get("content", "")
            if isinstance(content, list):
                parts.append(
                    " ".join(
                        p.get("text", "")
                        for p in content
                        if isinstance(p, dict) and p.get("type") == "text"
                    )
                )
            elif content is not None:
                parts.append(str(content))
        return "\n".join(parts)
    prompt = request_json.get("prompt", "")
    if isinstance(prompt, list):
        return "\n".join(str(p) for p in prompt)
    return str(prompt)


def _header(headers: Dict[str, str], key: Optional[str]) -> Optional[str]:
    """Case-insensitive header lookup (callers pass plain dicts whose key
    casing depends on the client's HTTP library)."""
    if not key:
        return None
    v = headers.get(key)
    if v is not None:
        return v
    lk = key.lower()
    for k, val in headers.items():
        if k.lower() == lk:
            return val
    return None


class ConsistentHashRing:
    """xxhash-based ring with virtual nodes; minimal remapping on membership change."""

    def __init__(self, vnodes: int = 160):
        self.vnodes = vnodes
        # pstlint: owned-by=task:update,_rebuild
        self._nodes: set = set()
        # pstlint: owned-by=task:update,_rebuild
        self._ring: List[Tuple[int, str]] = []
        # pstlint: owned-by=task:update,_rebuild
        self._hashes: List[int] = []

    def _rebuild(self) -> None:
        ring = []
        for node in self._nodes:
            for v in range(self.vnodes):
                ring.append((xxhash.xxh64_intdigest(f"{node}#{v}"), node))
        ring.sort()
        self._ring = ring
        self._hashes = [h for h, _ in ring]

    def update(self, nodes: Sequence[str]) -> None:
        new = set(nodes)
        if new != self._nodes:
            self._nodes = new
            self._rebuild()

    def get_node(self, key: str) -> Optional[str]:
        if not self._ring:
            return None
        h = xxhash.xxh64_intdigest(key)
        idx = bisect.bisect(self._hashes, h) % len(self._ring)
        return self._ring[idx][1]

    def get_node_bounded(
        self,
        key: str,
        loads: Dict[str, float],
        c: float = 2.0,
        allowed: Optional[set] = None,
    ) -> Optional[str]:
        """Consistent hashing with bounded loads (Mirrokni et al.): walk
        the ring clockwise from ``key``'s position and take the first
        node whose current load is under ``c ×`` the mean load, falling
        back to the first eligible node when everything is saturated.
        Replicated routers use this over the *shared* endpoint view +
        fleet-wide stats, so every replica computes the same (key → node)
        map AND a hot-spotted node sheds to the same successor on every
        replica.

        ``allowed`` constrains the pick to THIS replica's routable
        candidates (model match, not draining/sleeping, breaker-admitted)
        while the ring still hashes over the shared fleet view: replicas
        whose candidate sets agree pick identically, and a replica whose
        discovery lags simply walks to the nearest node it can actually
        route to — it never picks an engine it must not use."""
        if not self._ring:
            return None
        candidates = (
            self._nodes if allowed is None else self._nodes & set(allowed)
        )
        if not candidates:
            return None
        mean = sum(loads.get(n, 0.0) for n in candidates) / len(candidates)
        bound = c * max(mean, 1.0)
        h = xxhash.xxh64_intdigest(key)
        start = bisect.bisect(self._hashes, h) % len(self._ring)
        first_eligible: Optional[str] = None
        seen: set = set()
        for i in range(len(self._ring)):
            node = self._ring[(start + i) % len(self._ring)][1]
            if node in seen:
                continue
            seen.add(node)
            if node not in candidates:
                continue
            if first_eligible is None:
                first_eligible = node
            if loads.get(node, 0.0) < bound:
                return node
            if len(seen) == len(self._nodes):
                break
        return first_eligible


def apply_breaker_filter(endpoints: List[EndpointInfo]) -> List[EndpointInfo]:
    """Drop engines whose circuit breaker is refusing traffic.

    Fails open (registry semantics): when every candidate is refused, all
    of them come back rather than none, so a fleet-wide brownout surfaces
    upstream errors instead of a permanent router-side 503."""
    from ...resilience import get_breaker_registry

    registry = get_breaker_registry()
    if registry is None or not endpoints:
        return endpoints
    by_url = {e.url: e for e in endpoints}
    allowed = registry.filter_available(list(by_url))
    return [by_url[u] for u in allowed]


def filter_routable(
    endpoints: List[EndpointInfo],
    exclude: Optional[set] = None,
    apply_breakers: bool = True,
) -> List[EndpointInfo]:
    """Drop endpoints routing must not pick right now: explicitly excluded
    URLs (already tried this request), draining or warming engines, and
    engines whose circuit breaker is refusing traffic.

    The breaker filter fails open (see ``apply_breaker_filter``); explicit
    excludes, draining, and warming stay hard filters — routing a request
    to a warming engine lands it behind the precompile pass, exactly the
    cold-engine TTFT a rolling deploy must never produce.
    ``apply_breakers=False`` skips the breaker pass for routers that scope
    it per pool themselves (disagg P/D) — filtering the merged list would
    defeat fail-open for a pool that is entirely refused while the other
    pool keeps the list non-empty.
    """
    if exclude:
        endpoints = [e for e in endpoints if e.url not in exclude]
    endpoints = [
        e for e in endpoints
        if not getattr(e, "draining", False)
        and not getattr(e, "warming", False)
    ]
    if not apply_breakers:
        return endpoints
    return apply_breaker_filter(endpoints)


async def route_with_resilience(
    router: "RoutingInterface",
    endpoints: List[EndpointInfo],
    engine_stats: Dict[str, Any],
    request_stats: Dict[str, Any],
    headers: Dict[str, str],
    request_json: Optional[Dict[str, Any]] = None,
    exclude: Optional[set] = None,
) -> str:
    """The proxy's single entry into routing: consult circuit breakers and
    drain state before the policy picks an engine.

    The candidate filter is side-effect-free (``would_allow``); the probe
    slot of a half-open breaker is reserved only for the engine the policy
    actually picked (``allows``). If that slot was raced away, one
    alternative pick is made among the other candidates; if everything
    refuses (fleet-wide brownout) the original pick goes out anyway —
    fail open, same rationale as ``filter_available``.
    """
    from ...resilience import get_breaker_registry

    candidates = filter_routable(
        endpoints, exclude,
        apply_breakers=not getattr(router, "pool_scoped_breakers", False),
    )
    if not candidates:
        raise ValueError("no routable endpoints (all excluded or draining)")
    url = await router.route_request(
        candidates, engine_stats, request_stats, headers, request_json
    )
    registry = get_breaker_registry()
    if registry is None or registry.allows(url):
        return url
    others = [e for e in candidates if e.url != url]
    if others:
        alt = await router.route_request(
            others, engine_stats, request_stats, headers, request_json
        )
        if registry.allows(alt):
            return alt
    return url


class RoutingInterface(ABC, metaclass=SingletonABCMeta):
    @abstractmethod
    async def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats: Dict[str, Any],
        request_stats: Dict[str, Any],
        headers: Dict[str, str],
        request_json: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Pick the engine URL that should serve this request."""


class RoundRobinRouter(RoutingInterface):
    def __init__(self):
        if getattr(self, "_initialized", False):
            return
        self.req_id = 0
        # pstlint: owned-by=task:route_request
        self._sorted: List[EndpointInfo] = []
        self._last_hash: Optional[int] = None
        self._initialized = True

    async def route_request(self, endpoints, engine_stats, request_stats, headers, request_json=None) -> str:
        h = hash(tuple(e.url for e in endpoints))
        if h != self._last_hash:
            self._sorted = sorted(endpoints, key=lambda e: e.url)
            self._last_hash = h
        chosen = self._sorted[self.req_id % len(self._sorted)]
        self.req_id += 1
        return chosen.url


def _lowest_qps_url(endpoints: List[EndpointInfo], request_stats: Dict[str, Any]) -> str:
    def qps(e: EndpointInfo) -> float:
        rs = request_stats.get(e.url)
        return getattr(rs, "qps", float("inf")) if rs is not None else float("-inf")

    return min(endpoints, key=qps).url


class SessionRouter(RoutingInterface):
    """Sticky sessions via consistent hashing; QPS-based pick when no session."""

    def __init__(self, session_key: Optional[str] = None):
        if getattr(self, "_initialized", False):
            return
        if not session_key:
            raise ValueError("SessionRouter requires a session_key")
        self.session_key = session_key
        self.ring = ConsistentHashRing()
        self._initialized = True

    async def route_request(self, endpoints, engine_stats, request_stats, headers, request_json=None) -> str:
        session_id = _header(headers, self.session_key)
        local_urls = [e.url for e in endpoints]
        from ..state import get_state_backend

        backend = get_state_backend()
        if backend is not None and backend.shared:
            # Replicated routers hash over the UNION of every live
            # replica's endpoint view: replicas whose discovery views
            # momentarily diverge still map a session to the same engine
            # — and bounded loads shed a hot-spotted engine to the same
            # ring successor on every replica (fleet-wide stats). The
            # PICK stays constrained to this request's filtered candidate
            # list (``allowed``): the shared view only stabilizes ring
            # positions, it must never route around the model/drain/
            # breaker filters routing already applied.
            self.ring.update(backend.merged_endpoint_urls(local_urls))
            if session_id is not None:
                loads = {
                    url: max(getattr(rs, "qps", 0.0), 0.0)
                    for url, rs in request_stats.items()
                }
                url = self.ring.get_node_bounded(
                    session_id, loads, allowed=set(local_urls)
                )
                if url is None:
                    raise ValueError("no endpoints available")
                return url
            return _lowest_qps_url(endpoints, request_stats)
        self.ring.update(local_urls)
        if session_id is None:
            return _lowest_qps_url(endpoints, request_stats)
        url = self.ring.get_node(session_id)
        if url is None:
            raise ValueError("no endpoints available")
        return url


class KvawareRouter(RoutingInterface):
    """Route to the engine already holding the longest cached KV prefix.

    Asks the cache controller which engine instance has the most matching
    KV chunk hashes for the request's token prefix; below ``threshold``
    matched tokens, falls back to session-consistent hashing so cold
    prompts still spread evenly (reference behavior: KvawareRouter
    :221-338 with threshold fallback :301-319).
    """

    def __init__(
        self,
        controller_url: Optional[str] = None,
        session_key: Optional[str] = None,
        kv_aware_threshold: int = 2000,
        tokenizer_name: Optional[str] = None,
    ):
        if getattr(self, "_initialized", False):
            return
        self.controller_url = controller_url or "http://localhost:9000"
        self.session_key = session_key
        self.threshold = kv_aware_threshold
        self.tokenizer_name = tokenizer_name
        self._tokenizer = None
        self._fallback_ring = ConsistentHashRing()
        self._rr = 0
        self._session = None  # lazy long-lived ClientSession (hot path)
        self._initialized = True

    def _get_tokenizer(self, model: str):
        if self._tokenizer is None:
            from ...engine.tokenizer import get_tokenizer

            self._tokenizer = get_tokenizer(self.tokenizer_name or model)
        return self._tokenizer

    def _get_session(self):
        """One long-lived ClientSession for controller lookups. Opening a
        session (connector + cookie jar) per request is hot-path connection
        churn — the reference reuses its shared client the same way."""
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=2)
            )
        return self._session

    async def aclose(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
        self._session = None

    async def _lookup(
        self, model: str, token_ids: List[int],
        headers: Optional[Dict[str, str]] = None,
    ) -> Dict[str, int]:
        """Controller lookup: chunk-hash the prefix, return url->matched
        tokens. The lookup happens while routing a live request, so the
        request's id/trace context rides along (relay form of the hop
        contract) — a slow controller shows up inside that request's
        timeline instead of as unattributed routing latency."""
        from ...kvcache.hashing import chunk_hashes

        hashes = chunk_hashes(token_ids)
        if not hashes:
            return {}
        session = self._get_session()
        async with session.post(
            f"{self.controller_url}/lookup",
            json={"model": model, "hashes": hashes},
            headers=hop_headers(from_headers=headers or {}),
        ) as resp:
            resp.raise_for_status()
            data = await resp.json()
        return {k: int(v) for k, v in (data.get("matches") or {}).items()}

    async def route_request(self, endpoints, engine_stats, request_stats, headers, request_json=None) -> str:
        request_json = request_json or {}
        model = request_json.get("model", "")
        text = extract_prompt_text(request_json)
        try:
            tokenizer = self._get_tokenizer(model)
            token_ids = tokenizer.encode(text)
            matches = await self._lookup(model, token_ids, headers)
        except Exception as e:  # noqa: BLE001 — controller down → fallback
            logger.debug("kvaware lookup failed, falling back: %s", e)
            matches = {}
        by_url = {e.url: e for e in endpoints}
        live_matches = {u: n for u, n in matches.items() if u in by_url}
        if live_matches:
            best_url, best_tokens = max(live_matches.items(), key=lambda kv: kv[1])
            if best_tokens >= self.threshold:
                return best_url
        session_id = _header(headers, self.session_key)
        if session_id:
            self._fallback_ring.update(list(by_url))
            url = self._fallback_ring.get_node(session_id)
            if url:
                return url
        urls = sorted(by_url)
        url = urls[self._rr % len(urls)]
        self._rr += 1
        return url


class PrefixAwareRouter(RoutingInterface):
    """Route by longest prompt-prefix match in a shared hash trie."""

    def __init__(self):
        if getattr(self, "_initialized", False):
            return
        self.hashtrie = HashTrie()
        self._initialized = True

    async def route_request(self, endpoints, engine_stats, request_stats, headers, request_json=None) -> str:
        request_json = request_json or {}
        prompt = extract_prompt_text(request_json)
        available = {e.url for e in endpoints}
        from ..state import get_state_backend

        backend = get_state_backend()
        if backend is not None and backend.shared:
            # Apply peers' replicated insertions (chunk-hash paths, never
            # raw prompt text) before matching, so a session that bounced
            # replicas still finds the engine holding its warm prefix.
            for path, ep in backend.drain_prefix_inserts():
                await self.hashtrie.insert_hashes(path, ep)
        _, matched = await self.hashtrie.longest_prefix_match(prompt, available)
        candidates = matched or available
        # Tie-break on live engine queue depth (falls back to random).
        def load(url: str) -> float:
            es = engine_stats.get(url)
            if es is None:
                return 0.0
            return getattr(es, "num_running_requests", 0) + getattr(
                es, "num_queuing_requests", 0
            )

        min_load = min(load(u) for u in candidates)
        best = [u for u in candidates if load(u) == min_load]
        selected = random.choice(best)
        await self.hashtrie.insert(prompt, selected)
        if backend is not None and backend.shared:
            backend.publish_prefix_insert(
                self.hashtrie.hash_path(prompt), selected
            )
        return selected


class DisaggregatedPrefillRouter(RoutingInterface):
    """Split prefill and decode across disjoint engine pools by model label."""

    # Breaker filtering must happen after the label split, one pool at a
    # time: fail-open on the merged list would let healthy decode engines
    # mask an entirely-refused prefill pool (route_with_resilience skips
    # its own breaker pass when this is set).
    pool_scoped_breakers = True

    def __init__(
        self,
        prefill_model_labels: Optional[List[str]] = None,
        decode_model_labels: Optional[List[str]] = None,
    ):
        if getattr(self, "_initialized", False):
            return
        self.prefill_model_labels = prefill_model_labels or []
        self.decode_model_labels = decode_model_labels or []
        self._prefill_rr = 0
        self._decode_rr = 0
        self._initialized = True

    def _pick(self, pool: List[EndpointInfo], counter: int) -> str:
        if not pool:
            raise ValueError("no endpoints for requested disaggregated role")
        return sorted(pool, key=lambda e: e.url)[counter % len(pool)].url

    async def route_request(self, endpoints, engine_stats, request_stats, headers, request_json=None) -> str:
        request_json = request_json or {}
        is_prefill = request_json.get("max_tokens", 0) == 1
        if is_prefill:
            pool = [e for e in endpoints if e.model_label in self.prefill_model_labels]
            url = self._pick(apply_breaker_filter(pool), self._prefill_rr)
            self._prefill_rr += 1
        else:
            pool = [e for e in endpoints if e.model_label in self.decode_model_labels]
            url = self._pick(apply_breaker_filter(pool), self._decode_rr)
            self._decode_rr += 1
        return url


_ROUTER_CLASSES = (
    SessionRouter,
    RoundRobinRouter,
    KvawareRouter,
    PrefixAwareRouter,
    DisaggregatedPrefillRouter,
)


def initialize_routing_logic(routing_logic: RoutingLogic, **kwargs) -> RoutingInterface:
    if routing_logic == RoutingLogic.ROUND_ROBIN:
        return RoundRobinRouter()
    if routing_logic == RoutingLogic.SESSION_BASED:
        return SessionRouter(kwargs.get("session_key"))
    if routing_logic == RoutingLogic.KVAWARE:
        return KvawareRouter(
            kwargs.get("controller_url"),
            kwargs.get("session_key"),
            kwargs.get("kv_aware_threshold") or 2000,
            kwargs.get("tokenizer_name"),
        )
    if routing_logic == RoutingLogic.PREFIXAWARE:
        return PrefixAwareRouter()
    if routing_logic == RoutingLogic.DISAGGREGATED_PREFILL:
        return DisaggregatedPrefillRouter(
            kwargs.get("prefill_model_labels"), kwargs.get("decode_model_labels")
        )
    raise ValueError(f"invalid routing logic {routing_logic}")


def reconfigure_routing_logic(routing_logic: RoutingLogic, **kwargs) -> RoutingInterface:
    for cls in _ROUTER_CLASSES:
        cls.destroy()
    return initialize_routing_logic(routing_logic, **kwargs)


def get_routing_logic() -> RoutingInterface:
    for cls in _ROUTER_CLASSES:
        if cls in SingletonABCMeta._instances:
            return SingletonABCMeta._instances[cls]
    raise ValueError("routing logic not initialized")


def teardown_routing_logic() -> None:
    for cls in _ROUTER_CLASSES:
        cls.destroy()
