"""Routing policies: roundrobin, session, kvaware, prefixaware, disagg P/D.

Capability parity with the reference's
``src/vllm_router/routers/routing_logic.py`` (policy enum :49-54,
RoundRobinRouter :126-166, SessionRouter :169-218, KvawareRouter :221-338,
PrefixAwareRouter :341-417, DisaggregatedPrefillRouter :420-460,
initialize/reconfigure/get :464-520).

Redesigns:
- The consistent-hash ring is implemented natively (xxhash + bisect, 160
  virtual nodes per endpoint) instead of depending on ``uhashring``.
- KV-aware routing queries the production-stack-tpu cache controller
  (:mod:`production_stack_tpu.kvserver.controller`) over HTTP with
  token-chunk hashes computed by the shared scheme in
  :mod:`production_stack_tpu.kvcache.hashing`, instead of ZMQ into LMCache.
- Prefix-aware routing breaks ties by live engine load instead of randomly.
"""

from __future__ import annotations

import enum
import random
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional, Sequence, Tuple


from ...logging_utils import init_logger
from ...obs.tasks import spawn_owned
from ..service_discovery import EndpointInfo
from .hashtrie import HashTrie

logger = init_logger(__name__)


class RoutingLogic(enum.Enum):
    ROUND_ROBIN = "roundrobin"
    SESSION_BASED = "session"
    KVAWARE = "kvaware"
    PREFIXAWARE = "prefixaware"
    DISAGGREGATED_PREFILL = "disaggregated_prefill"
    FLEET = "fleet"


# App-scope key the active policy lives under (router.appscope).
_SCOPE_KEY = "routing_logic"


def extract_prompt_text(request_json: Dict[str, Any]) -> str:
    """Flatten a chat/completion body into routing text (stable across calls)."""
    if "messages" in request_json:
        parts = []
        for message in request_json.get("messages") or []:
            content = message.get("content", "")
            if isinstance(content, list):
                parts.append(
                    " ".join(
                        p.get("text", "")
                        for p in content
                        if isinstance(p, dict) and p.get("type") == "text"
                    )
                )
            elif content is not None:
                parts.append(str(content))
        return "\n".join(parts)
    prompt = request_json.get("prompt", "")
    if isinstance(prompt, list):
        return "\n".join(str(p) for p in prompt)
    return str(prompt)


def _header(headers: Dict[str, str], key: Optional[str]) -> Optional[str]:
    """Case-insensitive header lookup (callers pass plain dicts whose key
    casing depends on the client's HTTP library)."""
    if not key:
        return None
    v = headers.get(key)
    if v is not None:
        return v
    lk = key.lower()
    for k, val in headers.items():
        if k.lower() == lk:
            return val
    return None


# The ring lives in the dependency-free production_stack_tpu.hashring so
# the sharded KV client and the kvserver's anti-entropy sweep compute the
# same (key -> owner) placement without importing the router stack;
# re-exported here because this module is its historical home and the
# routing policies below are its primary consumer.
from ...hashring import ConsistentHashRing  # noqa: E402  (re-export)


def _run_trie_eviction(trie: HashTrie, url: str) -> None:
    """Run ``trie.remove_endpoint(url)`` on the running loop (reference
    held by the owned-task registry until done — asyncio keeps only weak
    task refs, and an unreferenced eviction suspended on a node lock
    could be collected mid-walk, leaving the phantom engine the churn
    contract forbids) or synchronously when no loop is running."""
    import asyncio

    coro = trie.remove_endpoint(url)
    try:
        asyncio.get_running_loop()
    except RuntimeError:  # no loop (sync caller in tests/CLI)
        asyncio.run(coro)
        return
    spawn_owned(coro, name=f"trie-evict:{url}")


def apply_breaker_filter(endpoints: List[EndpointInfo]) -> List[EndpointInfo]:
    """Drop engines whose circuit breaker is refusing traffic.

    Fails open (registry semantics): when every candidate is refused, all
    of them come back rather than none, so a fleet-wide brownout surfaces
    upstream errors instead of a permanent router-side 503."""
    from ...resilience import get_breaker_registry

    registry = get_breaker_registry()
    if registry is None or not endpoints:
        return endpoints
    by_url = {e.url: e for e in endpoints}
    allowed = registry.filter_available(list(by_url))
    return [by_url[u] for u in allowed]


def filter_routable(
    endpoints: List[EndpointInfo],
    exclude: Optional[set] = None,
    apply_breakers: bool = True,
) -> List[EndpointInfo]:
    """Drop endpoints routing must not pick right now: explicitly excluded
    URLs (already tried this request), draining or warming engines, and
    engines whose circuit breaker is refusing traffic.

    The breaker filter fails open (see ``apply_breaker_filter``); explicit
    excludes, draining, and warming stay hard filters — routing a request
    to a warming engine lands it behind the precompile pass, exactly the
    cold-engine TTFT a rolling deploy must never produce.
    ``apply_breakers=False`` skips the breaker pass for routers that scope
    it per pool themselves (disagg P/D) — filtering the merged list would
    defeat fail-open for a pool that is entirely refused while the other
    pool keeps the list non-empty.
    """
    if exclude:
        endpoints = [e for e in endpoints if e.url not in exclude]
    endpoints = [
        e for e in endpoints
        if not getattr(e, "draining", False)
        and not getattr(e, "warming", False)
    ]
    if not apply_breakers:
        return endpoints
    return apply_breaker_filter(endpoints)


async def route_with_resilience(
    router: "RoutingInterface",
    endpoints: List[EndpointInfo],
    engine_stats: Dict[str, Any],
    request_stats: Dict[str, Any],
    headers: Dict[str, str],
    request_json: Optional[Dict[str, Any]] = None,
    exclude: Optional[set] = None,
) -> str:
    """The proxy's single entry into routing: consult circuit breakers and
    drain state before the policy picks an engine.

    The candidate filter is side-effect-free (``would_allow``); the probe
    slot of a half-open breaker is reserved only for the engine the policy
    actually picked (``allows``). If that slot was raced away, one
    alternative pick is made among the other candidates; if everything
    refuses (fleet-wide brownout) the original pick goes out anyway —
    fail open, same rationale as ``filter_available``.
    """
    from ...resilience import get_breaker_registry

    candidates = filter_routable(
        endpoints, exclude,
        apply_breakers=not getattr(router, "pool_scoped_breakers", False),
    )
    if not candidates:
        raise ValueError("no routable endpoints (all excluded or draining)")
    url = await router.route_request(
        candidates, engine_stats, request_stats, headers, request_json
    )
    registry = get_breaker_registry()
    if registry is None or registry.allows(url):
        return url
    others = [e for e in candidates if e.url != url]
    if others:
        alt = await router.route_request(
            others, engine_stats, request_stats, headers, request_json
        )
        if registry.allows(alt):
            return alt
    return url


class RoutingInterface(ABC):
    """A routing policy. Plain classes — no ``SingletonMeta`` — created
    by ``initialize_routing_logic`` and resolved through the app scope
    (``router.appscope``), so two router apps in one process each run
    their OWN policy instance with zero shared state."""

    @abstractmethod
    async def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats: Dict[str, Any],
        request_stats: Dict[str, Any],
        headers: Dict[str, str],
        request_json: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Pick the engine URL that should serve this request."""

    def describe(self) -> dict:
        """Introspection view for GET /debug/fleet: at least the policy
        name; stateful policies override with their live table sizes."""
        return {"policy": type(self).__name__}

    @classmethod
    def destroy(cls) -> None:
        """Legacy SingletonMeta-era hook: drop the scoped policy when it
        is an instance of this class (tests use it to force a rebuild)."""
        from .. import appscope

        if isinstance(appscope.scoped_get(_SCOPE_KEY), cls):
            appscope.scoped_set(_SCOPE_KEY, None)


class RoundRobinRouter(RoutingInterface):
    def __init__(self):
        if getattr(self, "_initialized", False):
            return
        self.req_id = 0
        # pstlint: owned-by=task:route_request
        self._sorted: List[EndpointInfo] = []
        self._last_hash: Optional[int] = None
        self._initialized = True

    async def route_request(self, endpoints, engine_stats, request_stats, headers, request_json=None) -> str:
        h = hash(tuple(e.url for e in endpoints))
        if h != self._last_hash:
            self._sorted = sorted(endpoints, key=lambda e: e.url)
            self._last_hash = h
        chosen = self._sorted[self.req_id % len(self._sorted)]
        self.req_id += 1
        return chosen.url


def _lowest_qps_url(endpoints: List[EndpointInfo], request_stats: Dict[str, Any]) -> str:
    def qps(e: EndpointInfo) -> float:
        rs = request_stats.get(e.url)
        return getattr(rs, "qps", float("inf")) if rs is not None else float("-inf")

    return min(endpoints, key=qps).url


class SessionRouter(RoutingInterface):
    """Sticky sessions via consistent hashing; QPS-based pick when no session."""

    def __init__(self, session_key: Optional[str] = None):
        if getattr(self, "_initialized", False):
            return
        if not session_key:
            raise ValueError("SessionRouter requires a session_key")
        self.session_key = session_key
        self.ring = ConsistentHashRing()
        self._initialized = True

    async def route_request(self, endpoints, engine_stats, request_stats, headers, request_json=None) -> str:
        session_id = _header(headers, self.session_key)
        local_urls = [e.url for e in endpoints]
        from ..state import get_state_backend

        backend = get_state_backend()
        if backend is not None and backend.shared:
            # Replicated routers hash over the UNION of every live
            # replica's endpoint view: replicas whose discovery views
            # momentarily diverge still map a session to the same engine
            # — and bounded loads shed a hot-spotted engine to the same
            # ring successor on every replica (fleet-wide stats). The
            # PICK stays constrained to this request's filtered candidate
            # list (``allowed``): the shared view only stabilizes ring
            # positions, it must never route around the model/drain/
            # breaker filters routing already applied.
            self.ring.update(backend.merged_endpoint_urls(local_urls))
            if session_id is not None:
                loads = {
                    url: max(getattr(rs, "qps", 0.0), 0.0)
                    for url, rs in request_stats.items()
                }
                url = self.ring.get_node_bounded(
                    session_id, loads, allowed=set(local_urls)
                )
                if url is None:
                    raise ValueError("no endpoints available")
                return url
            return _lowest_qps_url(endpoints, request_stats)
        self.ring.update(local_urls)
        if session_id is None:
            return _lowest_qps_url(endpoints, request_stats)
        url = self.ring.get_node(session_id)
        if url is None:
            raise ValueError("no endpoints available")
        return url


class KvawareRouter(RoutingInterface):
    """Route to the engine already holding the longest cached KV prefix.

    Asks the cache controller which engine instance has the most matching
    KV chunk hashes for the request's token prefix; below ``threshold``
    matched tokens, falls back to session-consistent hashing so cold
    prompts still spread evenly (reference behavior: KvawareRouter
    :221-338 with threshold fallback :301-319).
    """

    def __init__(
        self,
        controller_url: Optional[str] = None,
        session_key: Optional[str] = None,
        kv_aware_threshold: int = 2000,
        tokenizer_name: Optional[str] = None,
    ):
        if getattr(self, "_initialized", False):
            return
        from . import scoring

        self.controller_url = controller_url or "http://localhost:9000"
        self.session_key = session_key
        self.threshold = kv_aware_threshold
        # Shared controller-lookup machinery (tokenize → chunk-hash →
        # POST /lookup with hop-contract relay headers, one long-lived
        # session): the same client fleet scoring uses.
        self.lookup_client = scoring.KvLookupClient(
            self.controller_url, tokenizer_name=tokenizer_name
        )
        self._fallback_ring = ConsistentHashRing()
        self._rr = 0
        self._initialized = True

    async def aclose(self) -> None:
        await self.lookup_client.aclose()

    async def route_request(self, endpoints, engine_stats, request_stats, headers, request_json=None) -> str:
        request_json = request_json or {}
        model = request_json.get("model", "")
        text = extract_prompt_text(request_json)
        try:
            matches = await self.lookup_client.lookup(model, text, headers)
        except Exception as e:  # noqa: BLE001 — controller down → fallback
            logger.debug("kvaware lookup failed, falling back: %s", e)
            matches = {}
        by_url = {e.url: e for e in endpoints}
        live_matches = {u: n for u, n in matches.items() if u in by_url}
        if live_matches:
            best_url, best_tokens = max(live_matches.items(), key=lambda kv: kv[1])
            if best_tokens >= self.threshold:
                return best_url
        session_id = _header(headers, self.session_key)
        if session_id:
            self._fallback_ring.update(list(by_url))
            url = self._fallback_ring.get_node(session_id)
            if url:
                return url
        urls = sorted(by_url)
        url = urls[self._rr % len(urls)]
        self._rr += 1
        return url


class PrefixAwareRouter(RoutingInterface):
    """Route by longest prompt-prefix match in a shared hash trie."""

    def __init__(self):
        if getattr(self, "_initialized", False):
            return
        self.hashtrie = HashTrie()
        self._initialized = True

    async def route_request(self, endpoints, engine_stats, request_stats, headers, request_json=None) -> str:
        request_json = request_json or {}
        prompt = extract_prompt_text(request_json)
        available = {e.url for e in endpoints}
        from ..state import get_state_backend

        backend = get_state_backend()
        if backend is not None and backend.shared:
            # Apply peers' replicated insertions (chunk-hash paths, never
            # raw prompt text) before matching, so a session that bounced
            # replicas still finds the engine holding its warm prefix.
            for path, ep in backend.drain_prefix_inserts():
                await self.hashtrie.insert_hashes(path, ep)
        _, matched = await self.hashtrie.longest_prefix_match(prompt, available)
        candidates = matched or available
        # Tie-break on live engine queue depth (falls back to random).
        def load(url: str) -> float:
            es = engine_stats.get(url)
            if es is None:
                return 0.0
            return getattr(es, "num_running_requests", 0) + getattr(
                es, "num_queuing_requests", 0
            )

        min_load = min(load(u) for u in candidates)
        best = [u for u in candidates if load(u) == min_load]
        selected = random.choice(best)
        await self.hashtrie.insert(prompt, selected)
        if backend is not None and backend.shared:
            backend.publish_prefix_insert(
                self.hashtrie.hash_path(prompt), selected
            )
        return selected

    def evict_endpoint(self, url: str) -> None:
        """Same one-step churn contract as FleetRouter: a removed engine
        leaves the trie immediately instead of lingering as a phantom
        deepest match."""
        _run_trie_eviction(self.hashtrie, url)


class FleetRouter(RoutingInterface):
    """Fused fleet routing: argmax of (expected prefix-hit tokens × KV
    headroom × canary-TTFT health) under a bounded-load constraint.

    One policy where the fleet previously had to choose between cache
    affinity (``prefixaware``/``kvaware``, which hot-spot a popular
    prefix onto one saturated engine) and load balance (``roundrobin``/
    ``session``, which throw away the prefix-hit rate). Scoring math
    lives in :mod:`.scoring`; this class orchestrates the decision:

    - Hit estimates come from the LOCAL hashtrie (zero extra hops); the
      kvserver ``/lookup`` is consulted only for prompts above the
      kvaware token threshold that the trie cannot already prove hot —
      below the threshold routing performs no network I/O at all.
    - KV headroom and canary TTFT read the already-running scraper and
      canary snapshots (no new blocking I/O per request).
    - The best scorer is skipped when its load exceeds ``load_factor ×``
      the mean candidate load (``pst_route_spill_total{reason}``) — the
      same bound `ConsistentHashRing.get_node_bounded` applies, so the
      score spill and the session-ring spill agree.
    - A session header pins the session's engine until its score decays
      below ``eviction_ratio ×`` the best score, it crosses the load
      bound, or it leaves the candidate set (draining / breaker-open /
      removed); the session then remaps THROUGH THE RING within that one
      routing decision (``pst_route_session_remap_total{reason}``) and
      the trie learns the new home on the same request.
    - Under a shared state backend the trie merges peers' replicated
      inserts, the ring hashes over the fleet-wide endpoint view, and
      loads come from the FLEET-MERGED request-stats view (peers'
      in-flight counts ride the request_stats gossip digest) so
      replicas spill identically.
    - Discovery removing an engine calls :meth:`evict_endpoint`: trie,
      session pins, and ring view drop it in one step (churn contract).
    """

    def __init__(
        self,
        session_key: Optional[str] = None,
        controller_url: Optional[str] = None,
        kv_aware_threshold: int = 2000,
        tokenizer_name: Optional[str] = None,
        eviction_ratio: float = 0.5,
        load_factor: float = 2.0,
    ):
        if getattr(self, "_initialized", False):
            return
        from . import scoring

        self.session_key = session_key
        self.threshold = kv_aware_threshold
        self.eviction_ratio = eviction_ratio
        self.load_factor = load_factor
        self.hashtrie = HashTrie()
        # One depth bound for every trie touch (match, insert, replicated
        # hash path): deep enough that the "local trie proves a hit above
        # threshold" lookup skip can fire, and a hard cap so a 500KB
        # prompt costs O(bound) trie nodes on the hot path — never O(len).
        self._max_chunks = max(
            64,
            int(self.threshold * scoring.CHARS_PER_TOKEN
                / self.hashtrie.chunk_size) + 1,
        )
        self.ring = ConsistentHashRing()
        self.pins = scoring.SessionPins()
        self.lookup_client = (
            scoring.KvLookupClient(controller_url, tokenizer_name=tokenizer_name)
            if controller_url else None
        )
        # Last computed scoring inputs, kept for the state backend's
        # endpoint-loads provider (gossiped to peer replicas) and for
        # introspection/tests. Single-writer: the routing decision path
        # (plus churn eviction dropping a removed engine's entries).
        # pstlint: owned-by=task:route_request,evict_endpoint
        self._last_scores: Dict[str, float] = {}
        # pstlint: owned-by=task:route_request,evict_endpoint
        self._last_loads: Dict[str, float] = {}
        # Introspection totals (GET /debug/fleet "routing" view): the
        # Prometheus counters beside them are per-process families a
        # snapshot cannot read back cheaply, so the router keeps its own.
        # pstlint: owned-by=task:route_request,_route_session
        self._spills_total = 0
        # pstlint: owned-by=task:_route_session
        self._remaps_total = 0
        self._initialized = True

    async def aclose(self) -> None:
        if self.lookup_client is not None:
            await self.lookup_client.aclose()

    # -- scoring inputs ----------------------------------------------------

    def _canary_ttfts(self) -> Dict[str, float]:
        """Local canary view merged with live peers' gossiped views,
        pessimistically (max): after a failed probe on ANY replica every
        replica scores that engine as slow, so replicated routers agree
        instead of splitting traffic on who happened to see the failure.
        Recovery converges the same way — each replica's next successful
        probe lowers its own published sample."""
        from ..services.canary import get_canary_prober
        from ..state import get_state_backend

        prober = get_canary_prober()
        view = dict(prober.ttft_view()) if prober is not None else {}
        backend = get_state_backend()
        peer_views = getattr(backend, "peer_canary_ttfts", None)
        if peer_views is not None and getattr(backend, "shared", False):
            for peer_view in peer_views().values():
                if not isinstance(peer_view, dict):
                    continue
                for url, ttft in peer_view.items():
                    try:
                        t = float(ttft)
                    except (TypeError, ValueError):
                        continue
                    view[url] = max(view.get(url, 0.0), t)
        return view

    async def _hit_tokens(
        self,
        prompt: str,
        urls: List[str],
        model: str,
        headers: Dict[str, str],
    ) -> Dict[str, float]:
        from . import metrics, scoring

        depths = await self.hashtrie.match_depths(
            prompt, set(urls), max_chunks=self._max_chunks
        )
        hit_tokens = {
            u: depths.get(u, 0) / scoring.CHARS_PER_TOKEN for u in urls
        }
        best_local = max(hit_tokens.values(), default=0.0)
        # The kvserver hop is gated THREE ways: a controller must be
        # configured, the prompt must be above the kvaware threshold
        # (short prompts can't hold threshold-many cached tokens — the
        # hot path stays network-free), and the local trie must not
        # already prove a hit that big.
        if self.lookup_client is None:
            metrics.lookup_skipped_total.labels(reason="disabled").inc()
            return hit_tokens
        if len(prompt) / scoring.CHARS_PER_TOKEN < self.threshold:
            metrics.lookup_skipped_total.labels(
                reason="below_threshold"
            ).inc()
            return hit_tokens
        if best_local >= self.threshold:
            metrics.lookup_skipped_total.labels(reason="local_hit").inc()
            return hit_tokens
        try:
            matches = await self.lookup_client.lookup(model, prompt, headers)
        except Exception as e:  # noqa: BLE001 — controller down → local view
            logger.debug("fleet kvserver lookup failed, scoring locally: %s", e)
            return hit_tokens
        for url, tokens in matches.items():
            if url in hit_tokens:
                hit_tokens[url] = max(hit_tokens[url], tokens)
        return hit_tokens

    # -- the decision ------------------------------------------------------

    async def route_request(self, endpoints, engine_stats, request_stats, headers, request_json=None) -> str:
        from ..state import get_state_backend
        from . import metrics, scoring

        request_json = request_json or {}
        prompt = extract_prompt_text(request_json)
        model = request_json.get("model", "")
        urls = [e.url for e in endpoints]
        backend = get_state_backend()
        shared = backend is not None and backend.shared
        if shared:
            # Apply peers' replicated trie insertions before matching and
            # hash the session ring over the fleet-wide endpoint view —
            # replicas whose discovery views momentarily diverge still
            # map a session identically (the pick stays constrained to
            # THIS request's filtered candidates).
            for path, ep in backend.drain_prefix_inserts():
                await self.hashtrie.insert_hashes(path, ep)
            self.ring.update(backend.merged_endpoint_urls(urls))
        else:
            self.ring.update(urls)

        hit_tokens = await self._hit_tokens(prompt, urls, model, headers)
        # The caller-passed stats are the FLEET-merged request-stats view
        # (get_request_stats defaults fleet=True): under a shared backend
        # live peers' in-flight counts are already summed in — one
        # provider, one merge, scoring reads the merged view
        # (docs/router-ha.md; the old endpoint_loads digest is gone).
        loads = scoring.fleet_loads(urls, request_stats or {})
        # Disagg leg hint (docs/disagg.md): the router's two-leg flow
        # stamps the pool on kv_transfer_params so the prefill leg scores
        # by compute/queue availability and the decode leg by KV
        # headroom/bandwidth; plain requests score the fused way.
        pool = (request_json.get("kv_transfer_params") or {}).get("pool")
        scores = scoring.score_engines(
            urls, hit_tokens, engine_stats or {}, self._canary_ttfts(),
            pool=pool if pool in ("prefill", "decode") else None,
        )
        bound = scoring.load_bound(loads, urls, self.load_factor)
        self._last_scores = dict(scores)
        self._last_loads = dict(loads)

        # Tenant class (docs/multi-tenancy.md): batch-tier requests may
        # not pin past the bounded-load rule (saturation sends them to
        # the least-loaded engine, not the affinity argmax) and their
        # session pins are the first evicted under pin-table pressure —
        # a batch flood cannot displace interactive affinity.
        from ...resilience.tenancy import TENANT_CLASS_HEADER, TIER_BATCH

        batch_tier = _header(headers, TENANT_CLASS_HEADER) == TIER_BATCH
        session_id = _header(headers, self.session_key)
        if session_id is not None:
            selected = self._route_session(
                session_id, urls, scores, loads, bound, batch_tier
            )
        else:
            selected, spill = scoring.pick_bounded(
                scores, loads, bound, batch_tier=batch_tier
            )
            if spill is not None:
                self._spills_total += 1
                metrics.spill_total.labels(reason=spill).inc()
        metrics.route_score.observe(max(scores.get(selected, 0.0), 0.0))
        # Insert bounded at the same depth the match walk reads: chunks
        # past _max_chunks would be pure write/lock cost no reader (local
        # match or replicated hash path) ever consumes.
        bounded = prompt[: self._max_chunks * self.hashtrie.chunk_size]
        await self.hashtrie.insert(bounded, selected)
        if shared:
            backend.publish_prefix_insert(
                self.hashtrie.hash_path(bounded, max_chunks=self._max_chunks),
                selected,
            )
        return selected

    def _route_session(
        self,
        session_id: str,
        urls: List[str],
        scores: Dict[str, float],
        loads: Dict[str, float],
        bound: float,
        batch_tier: bool = False,
    ) -> str:
        from . import metrics, scoring

        pinned = self.pins.get(session_id)
        best_score = max(scores.values(), default=0.0)
        if pinned is not None and pinned in scores:
            decayed = scores[pinned] < self.eviction_ratio * best_score
            overloaded = loads.get(pinned, 0.0) >= bound
            if not decayed and not overloaded:
                self.pins.pin(session_id, pinned, batch_tier=batch_tier)
                return pinned
            self._remaps_total += 1
            metrics.session_remap_total.labels(
                reason="score_decay" if decayed else "overload"
            ).inc()
        elif pinned is not None:
            # The pinned engine is no longer routable (draining, breaker
            # open, removed by discovery): remap within THIS decision.
            self._remaps_total += 1
            metrics.session_remap_total.labels(reason="unroutable").inc()
        remapped = self.ring.get_node_bounded(
            session_id, loads, c=self.load_factor, allowed=set(urls)
        )
        if remapped is None or remapped not in scores:
            remapped, spill = scoring.pick_bounded(
                scores, loads, bound, batch_tier=batch_tier
            )
            if spill is not None:
                self._spills_total += 1
                metrics.spill_total.labels(reason=spill).inc()
        if pinned is not None and remapped == pinned:
            # The ring handed the evicted session straight back (e.g. the
            # whole fleet is saturated): take the best scorer instead so
            # eviction always actually moves the session.
            others = {u: s for u, s in scores.items() if u != pinned}
            if others:
                remapped, _ = scoring.pick_bounded(
                    others, loads, bound, batch_tier=batch_tier
                )
        self.pins.pin(session_id, remapped, batch_tier=batch_tier)
        return remapped

    # -- introspection -----------------------------------------------------

    def describe(self) -> dict:
        """The fleet-routing view GET /debug/fleet serves: session-pin
        count, trie size, spill/remap totals, and the last scoring
        snapshot (scores + routed loads per engine)."""
        return {
            "policy": type(self).__name__,
            "session_pins": len(self.pins),
            "trie_nodes": self.hashtrie._node_count,
            "spills_total": self._spills_total,
            "session_remaps_total": self._remaps_total,
            "last_scores": {
                u: round(s, 6) for u, s in self._last_scores.items()
            },
            "last_loads": dict(self._last_loads),
        }

    # -- churn -------------------------------------------------------------

    def evict_endpoint(self, url: str) -> None:
        """Discovery removed an engine: drop it from the trie, the
        session-pin table, and the cached scoring views in one step, so
        no routing decision after this call can still prefer it."""
        self.pins.drop_endpoint(url)
        self._last_scores.pop(url, None)
        self._last_loads.pop(url, None)
        _run_trie_eviction(self.hashtrie, url)


class DisaggregatedPrefillRouter(RoutingInterface):
    """Split prefill and decode across disjoint engine pools by model label."""

    # Breaker filtering must happen after the label split, one pool at a
    # time: fail-open on the merged list would let healthy decode engines
    # mask an entirely-refused prefill pool (route_with_resilience skips
    # its own breaker pass when this is set).
    pool_scoped_breakers = True

    def __init__(
        self,
        prefill_model_labels: Optional[List[str]] = None,
        decode_model_labels: Optional[List[str]] = None,
    ):
        if getattr(self, "_initialized", False):
            return
        self.prefill_model_labels = prefill_model_labels or []
        self.decode_model_labels = decode_model_labels or []
        self._prefill_rr = 0
        self._decode_rr = 0
        self._initialized = True

    def _pick(self, pool: List[EndpointInfo], counter: int) -> str:
        if not pool:
            raise ValueError("no endpoints for requested disaggregated role")
        return sorted(pool, key=lambda e: e.url)[counter % len(pool)].url

    async def route_request(self, endpoints, engine_stats, request_stats, headers, request_json=None) -> str:
        request_json = request_json or {}
        is_prefill = request_json.get("max_tokens", 0) == 1
        if is_prefill:
            pool = [e for e in endpoints if e.model_label in self.prefill_model_labels]
            url = self._pick(apply_breaker_filter(pool), self._prefill_rr)
            self._prefill_rr += 1
        else:
            pool = [e for e in endpoints if e.model_label in self.decode_model_labels]
            url = self._pick(apply_breaker_filter(pool), self._decode_rr)
            self._decode_rr += 1
        return url


def evict_routing_endpoint(url: str) -> None:
    """Discovery-driven churn, one step: when an engine leaves the fleet
    (pod deleted, static backend failed its health probe), the active
    routing policy drops it from its trie/session-pin/score state — and
    the canary prober forgets its TTFT sample (a departed fast engine
    must not anchor the relative-health baseline forever) — immediately,
    the breaker/stats eviction's routing-side counterpart. No-op when
    routing is uninitialized or the policy keeps no per-engine state."""
    from ..services.canary import get_canary_prober

    prober = get_canary_prober()
    if prober is not None:
        prober.evict(url)
    try:
        router = get_routing_logic()
    except ValueError:
        return
    evict = getattr(router, "evict_endpoint", None)
    if evict is not None:
        evict(url)


def _build_routing_logic(routing_logic: RoutingLogic, **kwargs) -> RoutingInterface:
    if routing_logic == RoutingLogic.ROUND_ROBIN:
        return RoundRobinRouter()
    if routing_logic == RoutingLogic.SESSION_BASED:
        return SessionRouter(kwargs.get("session_key"))
    if routing_logic == RoutingLogic.KVAWARE:
        return KvawareRouter(
            kwargs.get("controller_url"),
            kwargs.get("session_key"),
            kwargs.get("kv_aware_threshold") or 2000,
            kwargs.get("tokenizer_name"),
        )
    if routing_logic == RoutingLogic.PREFIXAWARE:
        return PrefixAwareRouter()
    if routing_logic == RoutingLogic.DISAGGREGATED_PREFILL:
        return DisaggregatedPrefillRouter(
            kwargs.get("prefill_model_labels"), kwargs.get("decode_model_labels")
        )
    if routing_logic == RoutingLogic.FLEET:
        return FleetRouter(
            session_key=kwargs.get("session_key"),
            controller_url=kwargs.get("controller_url"),
            kv_aware_threshold=kwargs.get("kv_aware_threshold") or 2000,
            tokenizer_name=kwargs.get("tokenizer_name"),
            eviction_ratio=kwargs.get("fleet_eviction_ratio") or 0.5,
            load_factor=kwargs.get("fleet_load_factor") or 2.0,
        )
    raise ValueError(f"invalid routing logic {routing_logic}")


def initialize_routing_logic(routing_logic: RoutingLogic, **kwargs) -> RoutingInterface:
    """Build the policy and install it in the current app scope."""
    from .. import appscope

    return appscope.scoped_set(
        _SCOPE_KEY, _build_routing_logic(routing_logic, **kwargs)
    )


def reconfigure_routing_logic(routing_logic: RoutingLogic, **kwargs) -> RoutingInterface:
    import asyncio

    try:
        old = get_routing_logic()
    except ValueError:
        old = None
    # Routers holding a long-lived client session (kvaware, fleet) must
    # release it on hot reload, not only at app shutdown — otherwise
    # every dynamic-config apply leaks a connector.
    aclose = getattr(old, "aclose", None)
    if aclose is not None:
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            asyncio.run(aclose())
        else:
            spawn_owned(aclose(), name="routing-reconfigure-aclose")
    return initialize_routing_logic(routing_logic, **kwargs)


def get_routing_logic() -> RoutingInterface:
    from .. import appscope

    router = appscope.scoped_get(_SCOPE_KEY)
    if router is None:
        raise ValueError("routing logic not initialized")
    return router


def teardown_routing_logic() -> None:
    from .. import appscope

    appscope.scoped_set(_SCOPE_KEY, None)
