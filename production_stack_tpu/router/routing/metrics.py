"""Prometheus surface for fleet routing (``pst_route_*``).

Declared in ``obs/metric_registry.py`` and documented in
docs/observability.md ("Fleet routing" rows); the ``metric-registry``
pstlint check enforces the triangle.
"""

from prometheus_client import Counter, Histogram

# Score units are expected prefix-hit tokens (damped by headroom and
# canary health), so the buckets span "cold engine" (~the cold base) to
# "whole long context cached".
route_score = Histogram(
    "pst_route_score",
    "Fleet-routing score of the chosen engine per routing decision "
    "(expected prefix-hit tokens × KV headroom × canary health)",
    buckets=(1, 4, 16, 64, 256, 1024, 4096, 16384, 65536),
)
spill_total = Counter(
    "pst_route_spill",
    "Routing decisions where the best-scoring engine was NOT picked, by "
    "reason (load = best scorer above the bounded-load limit, spilled to "
    "the next-best; saturated = every candidate above the limit, "
    "fail-open to the best scorer)",
    ["reason"],
)
session_remap_total = Counter(
    "pst_route_session_remap",
    "Sticky sessions remapped off their pinned engine, by reason "
    "(unroutable = pin filtered out: draining/breaker-open/removed; "
    "score_decay = pin's score fell below the eviction ratio; "
    "overload = pin above the bounded-load limit)",
    ["reason"],
)
lookup_skipped_total = Counter(
    "pst_route_lookup_skipped",
    "Routing decisions that did NOT consult the kvserver /lookup, by "
    "reason (below_threshold = prompt under the kvaware token threshold "
    "— the zero-extra-hop common case; local_hit = the local trie "
    "already proves a hit above threshold; disabled = no controller "
    "configured)",
    ["reason"],
)
