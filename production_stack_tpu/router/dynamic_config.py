"""Hot-reload of discovery/routing config from a watched YAML/JSON file.

Capability parity with the reference's ``src/vllm_router/dynamic_config.py``
(DynamicRouterConfig :43-117, DynamicConfigWatcher._watch_worker :256-280,
reconfigure_all :236-244): the file is polled on an interval and, when its
content hash changes, discovery and routing singletons are torn down and
rebuilt from the new values.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
from dataclasses import dataclass
from typing import Any, Dict, Optional

import yaml

from ..logging_utils import init_logger
from ..utils import parse_comma_separated, parse_static_aliases
from .routing.logic import RoutingLogic, reconfigure_routing_logic
from .service_discovery import (
    ServiceDiscoveryType,
    get_service_discovery,
    reconfigure_service_discovery,
)

logger = init_logger(__name__)


@dataclass
class DynamicRouterConfig:
    """The subset of router config that may change at runtime."""

    service_discovery: Optional[str] = None
    static_backends: Optional[str] = None
    static_models: Optional[str] = None
    static_aliases: Optional[str] = None
    static_model_labels: Optional[str] = None
    routing_logic: Optional[str] = None
    session_key: Optional[str] = None
    kv_aware_threshold: Optional[int] = None
    fleet_eviction_ratio: Optional[float] = None
    fleet_load_factor: Optional[float] = None
    cache_controller_url: Optional[str] = None
    prefill_model_labels: Optional[str] = None
    decode_model_labels: Optional[str] = None

    @classmethod
    def from_file(cls, path: str) -> "DynamicRouterConfig":
        with open(path) as f:
            raw = yaml.safe_load(f) if path.endswith((".yaml", ".yml")) else json.load(f)
        fields = {k.replace("-", "_"): v for k, v in (raw or {}).items()}
        known = {f_ for f_ in cls.__dataclass_fields__}
        unknown = set(fields) - known
        if unknown:
            logger.warning("ignoring unknown dynamic config keys: %s", sorted(unknown))
        return cls(**{k: v for k, v in fields.items() if k in known})


def reconfigure_all(config: DynamicRouterConfig, args, app) -> None:
    """Apply a new dynamic config by rebuilding the affected singletons."""
    merged: Dict[str, Any] = {**vars(args)}
    for k, v in vars(config).items():
        if v is not None:
            merged[k] = v
    sd_type = merged.get("service_discovery", "static")
    if sd_type == "static":
        reconfigure_service_discovery(
            ServiceDiscoveryType.STATIC,
            app=app,
            urls=parse_comma_separated(merged.get("static_backends")),
            models=parse_comma_separated(merged.get("static_models")),
            aliases=parse_static_aliases(merged.get("static_aliases")),
            model_labels=parse_comma_separated(merged.get("static_model_labels")) or None,
            pools=parse_comma_separated(merged.get("static_pools")) or None,
        )
    else:
        reconfigure_service_discovery(
            ServiceDiscoveryType.K8S,
            app=app,
            namespace=merged.get("k8s_namespace", "default"),
            port=merged.get("k8s_port", 8000),
            label_selector=merged.get("k8s_label_selector"),
            k8s_service_discovery_type=merged.get("k8s_service_discovery_type", "pod-ip"),
        )
    reconfigure_routing_logic(
        RoutingLogic(merged.get("routing_logic", "roundrobin")),
        session_key=merged.get("session_key"),
        kv_aware_threshold=merged.get("kv_aware_threshold"),
        controller_url=merged.get("cache_controller_url"),
        fleet_eviction_ratio=merged.get("fleet_eviction_ratio"),
        fleet_load_factor=merged.get("fleet_load_factor"),
        prefill_model_labels=parse_comma_separated(merged.get("prefill_model_labels")) or None,
        decode_model_labels=parse_comma_separated(merged.get("decode_model_labels")) or None,
    )
    # (No endpoint-loads provider to repoint: fleet scoring reads the
    # fleet-merged request-stats view — the in-flight counts ride the
    # request_stats digest, which follows the app's monitor already.)
    logger.info("dynamic config applied: %s", config)


class DynamicConfigWatcher:
    """Polls the config file; re-applies on content change."""

    def __init__(self, path: str, interval: float, args, app):
        self.path = path
        self.interval = interval
        self.args = args
        self.app = app
        self._last_hash: Optional[str] = None
        # pstlint: task-owner=_task
        self._task = asyncio.get_event_loop().create_task(self._watch())
        self.current_config: Optional[DynamicRouterConfig] = None

    def _read_bytes(self) -> bytes:
        with open(self.path, "rb") as f:
            return f.read()

    async def _watch(self) -> None:
        while True:
            try:
                # Config files live on slow volumes (ConfigMap mounts, NFS)
                # often enough that a sync read in the poll loop would
                # stall live proxying — hence the executor hop.
                content = await asyncio.get_running_loop().run_in_executor(
                    None, self._read_bytes
                )
                digest = hashlib.sha256(content).hexdigest()
                if digest != self._last_hash:
                    if self._last_hash is not None:
                        logger.info("dynamic config change detected at %s", self.path)
                        config = DynamicRouterConfig.from_file(self.path)
                        reconfigure_all(config, self.args, self.app)
                        await get_service_discovery().start()
                        self.current_config = config
                    self._last_hash = digest
            except FileNotFoundError:
                logger.debug("dynamic config file %s missing", self.path)
            except Exception as e:  # noqa: BLE001
                logger.error("dynamic config reload failed: %s", e)
            await asyncio.sleep(self.interval)

    def get_current_config(self) -> Optional[DynamicRouterConfig]:
        return self.current_config

    def close(self) -> None:
        self._task.cancel()


# App-scoped (router.appscope): the watcher belongs to the app whose
# config file it polls.
_SCOPE_KEY = "dynamic_config_watcher"


def initialize_dynamic_config_watcher(
    path: str, interval: float, args, app
) -> DynamicConfigWatcher:
    from . import appscope

    return appscope.scoped_set(
        _SCOPE_KEY, DynamicConfigWatcher(path, interval, args, app)
    )


def get_dynamic_config_watcher() -> Optional[DynamicConfigWatcher]:
    from . import appscope

    return appscope.scoped_get(_SCOPE_KEY)
