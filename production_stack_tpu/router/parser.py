"""Router CLI: argparse flags, YAML/JSON bootstrap defaults, validation.

Capability parity with the reference's ``src/vllm_router/parsers/parser.py``
(parse_args :120-382, validate_args :85-117, YAML/JSON defaults merge
:47-68) and ``parsers/yaml_utils.py``.
"""

from __future__ import annotations

import argparse
import json
from typing import Any, Dict, Optional

import yaml

from ..logging_utils import init_logger
from ..utils import (
    parse_comma_separated,
    parse_static_aliases,
    parse_static_urls,
)

logger = init_logger(__name__)


def load_bootstrap_config(path: Optional[str]) -> Dict[str, Any]:
    """Load a YAML/JSON file whose keys are CLI flag names (dashes or
    underscores) used as argparse defaults."""
    if not path:
        return {}
    with open(path) as f:
        data = yaml.safe_load(f) if path.endswith((".yaml", ".yml")) else json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"bootstrap config {path} must be a mapping")
    return {k.replace("-", "_"): v for k, v in data.items()}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pst-router", description="TPU serving-fleet L7 router"
    )
    p.add_argument("--config", help="YAML/JSON file with default flag values")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8001)

    # Service discovery
    p.add_argument(
        "--service-discovery", choices=["static", "k8s"], default="static"
    )
    p.add_argument(
        "--k8s-service-discovery-type",
        choices=["pod-ip", "service-name"],
        default="pod-ip",
    )
    p.add_argument("--static-backends", help="comma-separated engine URLs")
    p.add_argument("--static-models", help="comma-separated model names (one per backend)")
    p.add_argument("--static-aliases", help="alias1:model1,alias2:model2")
    p.add_argument("--static-model-labels", help="comma-separated labels (one per backend)")
    p.add_argument("--static-model-types", help="comma-separated model types (chat|completion|embeddings|rerank|score)")
    p.add_argument("--static-pools",
                   help="comma-separated disagg pool per backend "
                        "(prefill|decode|fused; docs/disagg.md). Declaring "
                        "both a prefill and a decode pool makes the "
                        "two-leg disagg flow the fleet shape for every "
                        "generation request")
    p.add_argument("--static-backend-health-checks", action="store_true")
    p.add_argument("--health-check-interval", type=float, default=60.0,
                   help="seconds between static-backend health/drain probes")
    p.add_argument("--k8s-namespace", default="default")
    p.add_argument("--k8s-port", type=int, default=8000)
    p.add_argument("--k8s-label-selector", default=None)

    # Routing
    p.add_argument(
        "--routing-logic",
        choices=["roundrobin", "session", "kvaware", "prefixaware",
                 "disaggregated_prefill", "fleet"],
        default="roundrobin",
    )
    p.add_argument("--session-key", default=None)
    p.add_argument("--kv-aware-threshold", type=int, default=2000)
    # Fleet routing (docs/router.md "Fleet routing"): score = expected
    # prefix-hit tokens × KV headroom × canary health, argmax under
    # bounded loads; sessions pin until their engine's score decays.
    p.add_argument("--fleet-eviction-ratio", type=float, default=0.5,
                   help="a pinned session stays on its engine while that "
                        "engine's score is at least this fraction of the "
                        "best candidate's; below it the session remaps "
                        "through the consistent-hash ring (fleet routing)")
    p.add_argument("--fleet-load-factor", type=float, default=2.0,
                   help="bounded-load factor c: fleet routing never picks "
                        "an engine whose in-flight load exceeds c x the "
                        "mean candidate load (spills to the next-best "
                        "scorer instead)")
    p.add_argument("--cache-controller-url", default=None, help="KV cache controller base URL (kvaware routing)")
    p.add_argument("--tokenizer-name", default=None, help="tokenizer for kvaware prefix hashing (defaults to request model)")
    p.add_argument("--prefill-model-labels", default=None)
    p.add_argument("--decode-model-labels", default=None)
    # Disaggregated P/D handoff (docs/disagg.md): with overlap on (the
    # default) the decode leg dispatches CONCURRENTLY with the prefill leg
    # — the decode engine prefetches the streamed KV while the prefill is
    # still running and the prefill response is a completion signal, not a
    # gate. Off = the pre-overlap serial two-phase flow.
    p.add_argument("--disagg-overlap", dest="disagg_overlap",
                   action="store_true", default=True,
                   help="dispatch the disagg decode leg concurrently with "
                        "the prefill leg (streamed KV handoff overlapped "
                        "with prefill)")
    p.add_argument("--no-disagg-overlap", dest="disagg_overlap",
                   action="store_false")

    # Resilience (circuit breakers, retry/failover, admission control)
    p.add_argument("--admission-rate", type=float, default=0.0,
                   help="token-bucket refill rate in requests/sec (0 = unlimited)")
    p.add_argument("--admission-burst", type=int, default=0,
                   help="token-bucket capacity (0 = derive from rate)")
    p.add_argument("--admission-queue-size", type=int, default=128,
                   help="bounded admission queue length before 429 shedding")
    p.add_argument("--admission-queue-timeout", type=float, default=5.0,
                   help="max seconds a request may wait for admission")
    p.add_argument("--proxy-retries", type=int, default=2,
                   help="failover attempts after the first (0 = no retry)")
    p.add_argument("--retry-backoff", type=float, default=0.05,
                   help="base backoff seconds between proxy attempts (doubles)")
    p.add_argument("--proxy-connect-timeout", type=float, default=30.0,
                   help="seconds to wait for an upstream TCP connect "
                        "(0 = unlimited); connect failures retry/fail over")
    p.add_argument("--proxy-read-timeout", type=float, default=0.0,
                   help="max seconds between upstream socket reads "
                        "(0 = unlimited, the default — a quiet non-streamed "
                        "long generation is indistinguishable from a hung "
                        "engine, so only enable this when streaming)")
    p.add_argument("--breaker-failure-threshold", type=int, default=3,
                   help="consecutive failures before a backend breaker opens")
    p.add_argument("--breaker-recovery-time", type=float, default=10.0,
                   help="seconds an open breaker waits before half-open probing")
    p.add_argument("--breaker-half-open-probes", type=int, default=1,
                   help="concurrent live probes allowed while half-open")

    # Multi-tenant QoS (docs/multi-tenancy.md): tenant identity at
    # admission (API key / X-PST-Tenant), per-tenant weighted token
    # buckets + a weighted-fair (deficit round robin) admission queue
    # over priority tiers (interactive > batch), per-tenant deadline
    # defaults, and per-tenant usage metering.
    p.add_argument("--tenant-isolation", action="store_true", default=False,
                   help="derive a tenant per request and isolate overload "
                        "decisions per tenant: weighted per-tenant "
                        "admission buckets (shares of --admission-rate), "
                        "deficit-round-robin queueing over priority tiers, "
                        "tenant headers stamped on every engine hop, and "
                        "pst_tenant_* metering")
    p.add_argument("--tenant-config", default=None,
                   help="JSON/YAML file mapping tenant names to QoS specs "
                        "({tenants: {name: {weight, tier, rate, burst, "
                        "deadline_ms, api_keys}}}); unknown tenants ride "
                        "the default weight/tier")
    p.add_argument("--tenant-default-weight", type=float, default=1.0,
                   help="fair-share weight assigned to tenants without an "
                        "explicit spec (the whole ad-hoc population shares "
                        "one default-weight slice of --admission-rate)")
    p.add_argument("--tenant-default-tier", default="interactive",
                   choices=["interactive", "batch"],
                   help="priority tier assigned to tenants without an "
                        "explicit spec (interactive is strictly served "
                        "before batch)")
    p.add_argument("--tenant-header", default="X-PST-Tenant",
                   help="header carrying the client-declared tenant name "
                        "(API-key mapping from --tenant-config wins over "
                        "it; the router re-stamps the canonical headers "
                        "on every upstream hop)")

    # Deadlines & hedging (docs/resilience.md "Deadlines & hedging")
    p.add_argument("--default-deadline-ms", type=float, default=0.0,
                   help="latency budget assigned to requests without an "
                        "X-PST-Deadline-Ms header (0 = no deadline)")
    p.add_argument("--hedge-enabled", action="store_true", default=False,
                   help="hedge non-streaming idempotent requests against a "
                        "second engine after the hedge delay")
    p.add_argument("--hedge-delay-ms", type=float, default=0.0,
                   help="hedge trigger delay in ms (0 = derive from the "
                        "observed latency quantile)")
    p.add_argument("--hedge-quantile", type=float, default=0.9,
                   help="latency quantile the adaptive hedge delay tracks")
    p.add_argument("--hedge-max-outstanding-ratio", type=float, default=0.25,
                   help="cap outstanding hedges at this fraction of "
                        "outstanding primaries (floor 1)")

    # Stream resumption (docs/resilience.md "Stream resumption")
    p.add_argument("--stream-resume", action="store_true", default=False,
                   help="resume SSE streams broken by engine death on "
                        "another engine (journaled continuation) instead "
                        "of truncating")
    p.add_argument("--stream-resume-max-legs", type=int, default=2,
                   help="max continuation legs per streamed request")

    # Observability (docs/observability.md): in-process request tracing
    # with per-stage latency decomposition. Always SDK-free; spans mirror
    # to OpenTelemetry only when OTEL_EXPORTER_OTLP_ENDPOINT + SDK exist.
    p.add_argument("--tracing", dest="tracing", action="store_true",
                   default=True,
                   help="record per-request stage spans (traceparent "
                        "propagation, pst_stage_duration_seconds, "
                        "/debug/requests)")
    p.add_argument("--no-tracing", dest="tracing", action="store_false")
    p.add_argument("--debug-requests-buffer", type=int, default=256,
                   help="completed request timelines kept for "
                        "GET /debug/requests (0 disables the endpoint)")
    p.add_argument("--log-format", choices=["text", "json"], default="text",
                   help="log output format: 'json' emits one JSON object "
                        "per line enriched with trace_id/request_id/"
                        "tenant/component/replica_id from the request "
                        "context (docs/observability.md \"Structured "
                        "logging\"); 'text' keeps the colored "
                        "human-readable format")

    # SLO + canary layer (docs/observability.md "SLOs & alerting"):
    # pst_slo_* counters against the TTFT target, and a per-engine
    # synthetic-probe TTFT gauge the burn-rate alert rules read.
    p.add_argument("--slo-ttft-ms", type=float, default=200.0,
                   help="TTFT objective for pst_slo_ttft_within_target / "
                        "pst_slo_requests counters (0 disables SLO "
                        "accounting; default = the 200 ms north star)")
    p.add_argument("--canary-interval", type=float, default=0.0,
                   help="seconds between canary probes per engine "
                        "(pst_canary_ttft_seconds; 0 = off)")
    p.add_argument("--canary-timeout", type=float, default=5.0,
                   help="per-probe timeout; a timed-out canary counts as "
                        "a failure")
    # Capacity signals (docs/observability.md "Capacity signals"): the
    # autoscaler input — multi-window SLO burn rate, admission-queue
    # depth slope and gossip-merged fleet headroom at GET
    # /autoscale/signal + pst_capacity_* gauges.
    p.add_argument("--capacity-signal", dest="capacity_signal",
                   action="store_true", default=True,
                   help="serve GET /autoscale/signal (multi-window SLO "
                        "burn rate, queue-depth slope, fleet KV headroom, "
                        "replica hint) + pst_capacity_* gauges")
    p.add_argument("--no-capacity-signal", dest="capacity_signal",
                   action="store_false")

    # Router HA / replicated state (docs/router-ha.md): N router replicas
    # behave as one when they share routing state over the gossip backend.
    p.add_argument("--state-backend", choices=["memory", "gossip"],
                   default="memory",
                   help="routing-state backend: 'memory' (single replica, "
                        "the default) or 'gossip' (replicate breakers, "
                        "admission shares, stats, endpoint view, prefix "
                        "inserts and stream journals over HTTP between "
                        "router replicas)")
    p.add_argument("--state-peers", default=None,
                   help="comma-separated peer router base URLs "
                        "(http://host:port) or a re-resolved DNS spec "
                        "(dns://headless-service:port) for the gossip "
                        "backend")
    p.add_argument("--state-sync-interval", type=float, default=0.5,
                   help="seconds between gossip exchanges with each peer")
    p.add_argument("--state-peer-timeout", type=float, default=3.0,
                   help="seconds without a successful exchange before a "
                        "peer replica is considered dead (its admission "
                        "share is reclaimed and its journaled streams "
                        "become claimable)")
    p.add_argument("--state-replica-id", default=None,
                   help="stable replica identity for gossip (default: "
                        "random per process)")

    # Stats / metrics
    p.add_argument("--engine-stats-interval", type=float, default=15.0)
    p.add_argument("--request-stats-window", type=float, default=60.0)
    p.add_argument("--log-stats", action="store_true")
    p.add_argument("--log-stats-interval", type=float, default=10.0)

    # Files / batches
    p.add_argument("--enable-batch-api", action="store_true")
    p.add_argument(
        "--batch-db-path", default=None,
        help="SQLite path for the batch queue (default: <file-storage-path>/batches.sqlite)",
    )
    p.add_argument("--file-storage-class", default="local_file")
    p.add_argument("--file-storage-path", default="/tmp/pst_files")
    p.add_argument("--batch-processor", default="local")

    # Error reporting / tracing (reference parser.py:338-355; no-ops when
    # the optional SDKs are absent). OTel activates via the standard env
    # vars (OTEL_EXPORTER_OTLP_ENDPOINT, OTEL_SERVICE_NAME).
    p.add_argument("--sentry-dsn", default=None)
    p.add_argument("--sentry-traces-sample-rate", type=float, default=0.0)
    p.add_argument("--sentry-profile-session-sample-rate", type=float, default=0.0)

    # Dynamic config & callbacks & experimental
    p.add_argument("--dynamic-config-json", help="path to a hot-reloaded config file")
    p.add_argument("--callbacks", help="python file or module with pre/post request hooks")
    p.add_argument("--request-rewriter", default="noop")
    p.add_argument("--feature-gates", default="")
    p.add_argument("--pii-analyzer", default="regex",
                   choices=["regex", "presidio"])
    p.add_argument("--pii-types", default=None,
                   help="comma-separated PII types to block (default: all)")
    p.add_argument("--semantic-cache-model", default="all-MiniLM-L6-v2")
    p.add_argument("--semantic-cache-dir", default=None)
    p.add_argument("--semantic-cache-threshold", type=float, default=0.95)
    # auto: real embeddings via a backend's /v1/embeddings when one
    # answers, else the dependency-free hash embedder (VERDICT r3 #9).
    p.add_argument(
        "--semantic-cache-embedder",
        default="auto",
        choices=["auto", "engine", "hash"],
    )
    # Restrict engine embedding to a specific served model (e.g. a BERT
    # embedding pod); default: any backend's own model.
    p.add_argument("--semantic-cache-embed-model", default=None)

    # Misc
    p.add_argument("--api-key", default=None, help="require this bearer token from clients")
    p.add_argument("--log-level", default="info")
    return p


def validate_args(args: argparse.Namespace) -> None:
    if args.service_discovery == "static":
        if not args.static_backends:
            raise ValueError("static discovery requires --static-backends")
        if not args.static_models:
            raise ValueError("static discovery requires --static-models")
        urls = parse_static_urls(args.static_backends)
        models = parse_comma_separated(args.static_models)
        if len(urls) != len(models):
            raise ValueError(
                f"--static-backends ({len(urls)}) and --static-models "
                f"({len(models)}) must have the same length"
            )
        if args.static_model_labels:
            labels = parse_comma_separated(args.static_model_labels)
            if len(labels) != len(urls):
                raise ValueError("--static-model-labels length mismatch")
        if args.static_backend_health_checks and not args.static_model_types:
            raise ValueError(
                "--static-backend-health-checks requires --static-model-types"
            )
        if args.static_pools:
            pools = parse_comma_separated(args.static_pools)
            if len(pools) != len(urls):
                raise ValueError("--static-pools length mismatch")
            bad = [x for x in pools if x not in ("prefill", "decode", "fused")]
            if bad:
                raise ValueError(
                    f"--static-pools entries must be prefill|decode|fused "
                    f"(got {bad})"
                )
    if args.admission_rate < 0:
        raise ValueError("--admission-rate must be >= 0")
    if args.proxy_retries < 0:
        raise ValueError("--proxy-retries must be >= 0")
    if args.breaker_failure_threshold < 1:
        raise ValueError("--breaker-failure-threshold must be >= 1")
    if args.default_deadline_ms < 0:
        raise ValueError("--default-deadline-ms must be >= 0")
    if args.tenant_config and not args.tenant_isolation:
        raise ValueError("--tenant-config requires --tenant-isolation")
    if args.tenant_default_weight <= 0:
        raise ValueError("--tenant-default-weight must be > 0")
    if args.debug_requests_buffer < 0:
        raise ValueError("--debug-requests-buffer must be >= 0")
    if args.slo_ttft_ms < 0:
        raise ValueError("--slo-ttft-ms must be >= 0")
    if args.canary_interval < 0:
        raise ValueError("--canary-interval must be >= 0")
    if args.canary_timeout <= 0:
        raise ValueError("--canary-timeout must be > 0")
    if args.hedge_max_outstanding_ratio < 0:
        raise ValueError("--hedge-max-outstanding-ratio must be >= 0")
    if not (0.0 < args.hedge_quantile < 1.0):
        raise ValueError("--hedge-quantile must be in (0, 1)")
    if args.stream_resume_max_legs < 1:
        raise ValueError("--stream-resume-max-legs must be >= 1")
    if args.state_sync_interval <= 0:
        raise ValueError("--state-sync-interval must be > 0")
    if args.state_peer_timeout <= 0:
        raise ValueError("--state-peer-timeout must be > 0")
    if args.state_peers and args.state_backend != "gossip":
        raise ValueError("--state-peers requires --state-backend gossip")
    if args.routing_logic == "session" and not args.session_key:
        raise ValueError("session routing requires --session-key")
    if not (0.0 < args.fleet_eviction_ratio <= 1.0):
        raise ValueError("--fleet-eviction-ratio must be in (0, 1]")
    if args.fleet_load_factor <= 1.0:
        raise ValueError("--fleet-load-factor must be > 1")
    if args.routing_logic == "disaggregated_prefill":
        if not (args.prefill_model_labels and args.decode_model_labels):
            raise ValueError(
                "disaggregated_prefill routing requires --prefill-model-labels "
                "and --decode-model-labels"
            )


def parse_args(argv=None) -> argparse.Namespace:
    parser = build_parser()
    # Two-pass: read --config first, re-parse with file values as defaults.
    pre, _ = parser.parse_known_args(argv)
    if pre.config:
        defaults = load_bootstrap_config(pre.config)
        known = {a.dest for a in parser._actions}
        unknown = set(defaults) - known
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        parser.set_defaults(**defaults)
    args = parser.parse_args(argv)
    validate_args(args)
    args.static_aliases_parsed = parse_static_aliases(args.static_aliases)
    return args
