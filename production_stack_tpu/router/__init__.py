"""L7 request router for the TPU serving fleet.

Capability parity with the reference's ``src/vllm_router`` (an
OpenAI-compatible FastAPI router over vLLM pods); this implementation is
asyncio-native on aiohttp.web and fronts ``production_stack_tpu.engine``
pods (or anything speaking the same OpenAI + /metrics surface).
"""
