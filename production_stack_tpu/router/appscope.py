"""App-scoped service registry: the sanctioned home for router "singletons".

Until router HA, every router service (discovery, routing logic, stats,
canary, feature gates, ...) lived in a module-level global rebound by an
``initialize_*`` function. With several router apps in one process (the
multi-replica tests, and eventually in-process replica harnesses) that
pattern is *last-app-wins*: the second ``create_app`` silently repoints
every ambient lookup at its own instances and the first app routes with
someone else's state.

This module replaces those globals with ONE context-bound scope:

- A *scope* is any mutable mapping. The app factory binds the
  ``aiohttp.web.Application`` itself (it is a ``MutableMapping``), so
  ``scoped_set("service_discovery", sd)`` and ``app["service_discovery"]``
  are the same storage — app-factory injection and ambient lookup can
  never disagree.
- ``bind_scope`` is called at three points: ``initialize_all`` (so
  bootstrap-time lookups resolve while the app is being wired),
  ``on_startup`` (so background loops spawned there inherit THEIR app's
  scope via ``contextvars`` task inheritance), and per request by the
  state middleware (so handler code resolves the serving app's scope).
- Bare callers with no bound scope (unit tests that call
  ``initialize_service_discovery`` directly) get an implicit dict scope
  for their context — the old module-global semantics, but per context
  instead of per process.

The ``app-scope`` pstlint check (docs/static-analysis.md) enforces the
other half: new module-level mutable state or ``global`` rebinds in
``router/`` fail CI, so the last-app-wins pattern cannot grow back.
"""

from __future__ import annotations

from contextvars import ContextVar, Token
from typing import Any, MutableMapping, Optional

Scope = MutableMapping[str, Any]

_scope: ContextVar[Optional[Scope]] = ContextVar("pst_app_scope", default=None)


def bind_scope(scope: Scope) -> "Token[Optional[Scope]]":
    """Bind ``scope`` (usually the aiohttp app) for the current context;
    returns the token for :func:`unbind_scope`."""
    return _scope.set(scope)


def unbind_scope(token: "Token[Optional[Scope]]") -> None:
    _scope.reset(token)


def current_scope(create: bool = False) -> Optional[Scope]:
    """The bound scope, or (with ``create=True``) a fresh implicit dict
    scope bound to the current context when none exists yet."""
    scope = _scope.get()
    if scope is None and create:
        scope = {}
        _scope.set(scope)
    return scope


def scoped_set(key: str, value: Any) -> Any:
    """Store ``value`` under ``key`` in the current scope (creating an
    implicit scope for bare callers). Returns ``value``."""
    scope = current_scope(create=True)
    assert scope is not None
    scope[key] = value
    return value


def scoped_get(key: str, default: Any = None) -> Any:
    scope = _scope.get()
    if scope is None:
        return default
    return scope.get(key, default)
