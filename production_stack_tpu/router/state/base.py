"""StateBackend: the router's replicated-state interface.

Every piece of router-side mutable state that must be *coherent across
replicas* — fleet-wide admission counts, circuit-breaker verdicts, the
request-stats windows routing reads, the shared endpoint view the
consistent-hash ring is built over, prefix-trie insertions, and
stream-resume journal checkpoints — flows through this interface. The
base class IS the single-replica (in-memory) implementation: every
coordination primitive degenerates to "just me", which preserves the
pre-HA router behavior byte for byte. :class:`~.gossip.GossipStateBackend`
overrides the coordination points so N replicas behave as one router
(docs/router-ha.md has the consistency model and the failure matrix).

Design note — why domain-level methods instead of a raw key/value store:
the replicated structures have *different* merge semantics (admission
wants rate splitting, breakers want freshest-state-wins per engine,
stats want additive merge, journals want owner-death claim-once). A KV
facade would push those semantics into every consumer; this interface
keeps each consumer's call site one line and the merge policy in one
place per structure.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# Provider keys the sync layer pulls local snapshots from each round.
PROVIDER_REQUEST_STATS = "request_stats"
PROVIDER_ENDPOINTS = "endpoints"
PROVIDER_BREAKERS = "breakers"
# (There is deliberately NO endpoint-loads provider: fleet routing's
# bounded-load input is the in_prefill/in_decoding counts already riding
# the request_stats digest — one provider, one merge; docs/router-ha.md.)
# Canary-probe TTFT per engine (url -> seconds): the health input fleet
# scoring multiplies in. Replicated so replicas whose probes diverged
# (only one of them saw an engine's failed probe) still score that
# engine the same way.
PROVIDER_CANARY_TTFT = "canary_ttft"
# This replica's fleet-introspection snapshot (engines + routing +
# tenants view; router/services/fleet.py): replicated so GET /debug/fleet
# answers with the same gossip-merged deployment picture from every
# replica — one surface instead of hand-joining N routers' local views.
PROVIDER_FLEET_SNAPSHOT = "fleet_snapshot"


class StateBackend:
    """Single-replica (in-memory) backend; also the interface contract.

    ``shared`` is the capability flag consumers branch on: ``False``
    means every method is a local no-op/identity and the router runs
    exactly as it did before this interface existed.
    """

    name = "memory"
    shared = False

    def __init__(self, replica_id: Optional[str] = None) -> None:
        self._replica_id = replica_id or uuid.uuid4().hex[:12]
        # Snapshot providers the sync layer reads; registration happens at
        # app bootstrap (initialize_all) and is read-only afterwards.
        # pstlint: owned-by=task:register_provider
        self._providers: Dict[str, Callable[[], Any]] = {}

    # -- lifecycle ---------------------------------------------------------

    async def start(self, app: Any = None) -> None:
        """Begin syncing (gossip loop); no-op for the in-memory backend."""

    async def close(self) -> None:
        """Stop syncing and release network resources."""

    def synced(self) -> bool:
        """Whether this replica's state view is good enough to serve —
        the router ``/ready`` contract (503 ``state_sync`` until True).
        A single replica is trivially synced."""
        return True

    async def sync_now(self) -> None:
        """Force one immediate sync round (used by router drain so
        journal checkpoints reach the survivors before shutdown)."""

    # -- membership --------------------------------------------------------

    def replica_id(self) -> str:
        return self._replica_id

    def live_replica_count(self) -> int:
        """Replicas currently participating (self included)."""
        return 1

    def admission_share(self) -> float:
        """Fraction of the *global* admission rate this replica may admit.

        Rate splitting: each live replica admits ``global_rate / n``, so
        the fleet-wide admit rate equals the configured limit regardless
        of replica count, and a replica death never doubles the fleet's
        effective limit (the survivors' shares grow only after the dead
        peer ages out of the membership view)."""
        return 1.0

    # -- providers (local snapshots the sync layer gossips out) ------------

    def register_provider(self, key: str, fn: Callable[[], Any]) -> None:
        self._providers[key] = fn

    def _provide(self, key: str, default: Any) -> Any:
        fn = self._providers.get(key)
        if fn is None:
            return default
        try:
            return fn()
        except Exception:  # noqa: BLE001 — a sync round must never die on a provider
            return default

    # -- circuit breakers --------------------------------------------------

    def remote_breaker_state(self, url: str) -> Optional[str]:
        """The most severe breaker state any *live peer* reports for
        ``url`` ("open" blocks routing fleet-wide), or None when no peer
        has an opinion. Single replica: no peers, no opinion."""
        return None

    # -- request stats -----------------------------------------------------

    def peer_request_stats(self) -> Dict[str, Dict[str, dict]]:
        """replica-id -> {engine-url -> compact stats dict} for live
        peers; the monitor merges these additively into its local view."""
        return {}

    # -- canary health (fleet-scoring health input) ------------------------

    def peer_canary_ttfts(self) -> Dict[str, Dict[str, float]]:
        """replica-id -> {engine-url -> last canary TTFT seconds} for
        live peers; fleet scoring merges these pessimistically (max) into
        its local view so replica scoring agrees after a failed probe.
        Single replica: no peers, no remote opinion."""
        return {}

    # -- fleet introspection snapshots (GET /debug/fleet) ------------------

    def peer_fleet_snapshots(self) -> Dict[str, dict]:
        """replica-id -> that replica's local fleet snapshot (engines /
        routing / tenants view) for live peers; ``/debug/fleet`` merges
        these with the local snapshot so every replica serves the same
        deployment picture modulo one sync interval. Single replica: no
        peers, nothing to merge."""
        return {}

    # -- endpoint view -----------------------------------------------------

    def merged_endpoint_urls(self, local: Sequence[str]) -> List[str]:
        """The fleet-wide endpoint URL set (union over live replicas) the
        consistent-hash ring is built from, so replicas whose discovery
        views momentarily diverge still hash sessions identically."""
        return list(local)

    # -- prefix trie -------------------------------------------------------

    def publish_prefix_insert(self, path: Sequence[int], endpoint: str) -> None:
        """Record a prefix-trie insertion (chunk-hash path -> endpoint)
        for replication to peers."""

    def drain_prefix_inserts(self) -> List[Tuple[List[int], str]]:
        """Remote insertions accumulated since the last drain, to be
        applied to the local trie."""
        return []

    # -- stream-resume journals --------------------------------------------

    def checkpoint_journal(self, request_id: str, snapshot: dict) -> None:
        """Checkpoint an in-flight stream's journal so a surviving
        replica can resume it if this replica dies mid-stream."""

    def drop_journal(self, request_id: str) -> None:
        """The stream ended (completed, truncated, or client gone):
        retire its checkpoint everywhere."""

    def claim_remote_journal(self, request_id: str) -> Optional[dict]:
        """Claim the journal checkpoint for ``request_id`` if its owning
        replica is DEAD (claim-once: the checkpoint is retired so two
        survivors cannot both resume it). Returns ``{"snap": {...}}``
        for a usable checkpoint, ``{"stale": True}`` when a checkpoint
        existed but can no longer be spliced (too old), and ``None``
        when there is nothing to take over (no checkpoint, or the owner
        is still alive and streaming it)."""
        return None

    # -- introspection (/ready, /engines, tests) ---------------------------

    def describe(self) -> dict:
        return {
            "backend": self.name,
            "replica": self.replica_id(),
            "replicas": self.live_replica_count(),
            "synced": self.synced(),
        }
