"""Gossip-over-HTTP replicated state backend: N router replicas as one.

Why gossip-over-HTTP and not a Redis-protocol store (the decision
ISSUE/docs require): the router already speaks HTTP on an asyncio loop,
so replication rides the existing server + client machinery with ZERO
new dependencies, no extra stateful pod in the helm chart, no Redis
failover story (which would just move the SPOF), and full testability
in-process (two backends in one event loop) and in CI (two router
subprocesses). The price is eventual consistency with a bounded
staleness of ~one sync interval — acceptable for every structure routed
through the backend, because each was *chosen* to tolerate it (rate
splitting, freshest-breaker-wins, additive stats, claim-once journals).
docs/router-ha.md spells out the consistency model per structure.

Protocol: every ``--state-sync-interval`` seconds each replica POSTs its
digest to every peer's ``POST /_state/gossip`` and merges the digest the
peer answers with — a symmetric anti-entropy exchange, so one round
converges both directions even if only one side can dial the other.
Peers are configured as explicit URLs (``http://host:port``) or a DNS
name re-resolved every round (``dns://name:port`` — the k8s headless
service path, so scale-out needs no config change). A replica that
reaches its own address recognizes itself by replica id and skips it.

Membership is implicit: a peer is *live* while its last exchange is
younger than ``--state-peer-timeout``; a SIGKILLed replica ages out and
the survivors' admission shares and journal-takeover rights adjust on
the next round. There is no leader and no quorum — any subset of
replicas keeps serving (availability over strict consistency; the
routing data plane must never block on coordination).
"""

# pstlint: disable-file=hop-contract(state-sync exchanges are replica-to-replica control plane: there is no client request whose id/trace/deadline could be relayed; exchanges are identified by replica id instead)

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlparse

import aiohttp

from ...logging_utils import init_logger
from ...obs.tasks import spawn_owned
from .base import (
    PROVIDER_BREAKERS,
    PROVIDER_CANARY_TTFT,
    PROVIDER_ENDPOINTS,
    PROVIDER_FLEET_SNAPSHOT,
    PROVIDER_REQUEST_STATS,
    StateBackend,
)
from . import metrics

logger = init_logger(__name__)

GOSSIP_PATH = "/_state/gossip"

# Bounded replication queues/tables: a router must stay O(fleet), never
# O(traffic history).
MAX_PREFIX_OUT = 512
MAX_PREFIX_IN = 2048
MAX_JOURNALS = 256


class _Peer:
    """Last-known state of one remote replica, keyed by replica id."""

    __slots__ = (
        "seen", "endpoints", "stats", "breakers", "canary", "fleet",
    )

    def __init__(self) -> None:
        self.seen = 0.0  # monotonic receipt time of the last digest
        # pstlint: owned-by=task:_apply
        self.endpoints: set = set()
        # pstlint: owned-by=task:_apply
        self.stats: Dict[str, dict] = {}
        # pstlint: owned-by=task:_apply
        self.breakers: Dict[str, str] = {}
        # Fleet-routing scoring input (routed-in-flight per engine).
        # pstlint: owned-by=task:_apply
        # Canary TTFT per engine (fleet-scoring health input; replicated
        # so replica scoring agrees after a failed probe).
        # pstlint: owned-by=task:_apply
        self.canary: Dict[str, float] = {}
        # Fleet-introspection snapshot (GET /debug/fleet merge input).
        # pstlint: owned-by=task:_apply
        self.fleet: dict = {}


class _Target:
    """Exchange bookkeeping for one resolved peer address."""

    __slots__ = ("attempted", "succeeded", "is_self")

    def __init__(self) -> None:
        self.attempted = False
        self.succeeded = False
        self.is_self = False


class _Journal:
    __slots__ = ("owner", "snap", "ts", "seen")

    def __init__(self, owner: str, snap: dict, ts: float, seen: float) -> None:
        self.owner = owner
        self.snap = snap
        self.ts = ts      # owner wall clock at checkpoint (informational)
        self.seen = seen  # LOCAL monotonic time: staleness never trusts peer clocks


class GossipStateBackend(StateBackend):
    name = "gossip"
    shared = True

    def __init__(
        self,
        peers: Sequence[str],
        replica_id: Optional[str] = None,
        sync_interval: float = 0.5,
        peer_timeout: float = 3.0,
        ready_grace: Optional[float] = None,
        journal_ttl: float = 60.0,
        api_key: Optional[str] = None,
    ) -> None:
        super().__init__(replica_id=replica_id)
        # pstlint: owned-by=task:__init__
        self.peer_specs = [p.strip() for p in peers if p and p.strip()]
        self.sync_interval = max(sync_interval, 0.05)
        self.peer_timeout = max(peer_timeout, self.sync_interval * 2)
        # How long a fresh replica may wait for unreachable peers before
        # declaring itself ready anyway (a lone survivor must come up).
        self.ready_grace = (
            ready_grace if ready_grace is not None
            else max(self.peer_timeout * 2, 5.0)
        )
        self.journal_ttl = journal_ttl
        self.api_key = api_key

        # Single-writer surfaces (asyncio single-thread; the lock-discipline
        # check keeps it that way as this package grows).
        # pstlint: owned-by=task:_apply,_prune
        self._peers: Dict[str, _Peer] = {}
        # pstlint: owned-by=task:_sync_with,_targets_for
        self._targets: Dict[str, _Target] = {}
        # pstlint: owned-by=task:checkpoint_journal,drop_journal,claim_remote_journal,_apply,_prune
        self._journals: Dict[str, _Journal] = {}
        # pstlint: owned-by=task:drop_journal,claim_remote_journal,_prune
        self._drops: Deque[Tuple[str, float]] = deque(maxlen=1024)
        # pstlint: owned-by=task:publish_prefix_insert
        self._prefix_out: Deque[Tuple[int, List[int], str]] = deque(
            maxlen=MAX_PREFIX_OUT
        )
        # pstlint: owned-by=task:_apply,drain_prefix_inserts
        self._prefix_in: Deque[Tuple[List[int], str]] = deque(maxlen=MAX_PREFIX_IN)
        # pstlint: owned-by=task:_apply,_prune
        self._applied_seq: Dict[str, int] = {}
        self._prefix_seq = 0
        self._session: Optional[aiohttp.ClientSession] = None
        self._task: Optional[asyncio.Task] = None
        self._started: Optional[float] = None
        self._synced = not self.peer_specs  # no peers -> trivially synced
        self._rounds = 0

    # -- lifecycle ---------------------------------------------------------

    async def start(self, app: Any = None) -> None:
        if self._task is not None:
            return
        self._started = time.monotonic()
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=max(self.sync_interval * 4, 2.0))
        )
        self._task = spawn_owned(self._loop(), name="gossip-state-sync")
        logger.info(
            "gossip state backend up: replica=%s peers=%s interval=%.2fs "
            "peer_timeout=%.2fs",
            self.replica_id(), self.peer_specs, self.sync_interval,
            self.peer_timeout,
        )

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._session is not None:
            await self._session.close()
            self._session = None

    def synced(self) -> bool:
        if self._synced:
            return True
        now = time.monotonic()
        if self._rounds > 0 and any(
            t.succeeded or t.is_self for t in self._targets.values()
        ):
            # At least one full round ran and some peer answered: the
            # fleet view is as good as it gets this interval.
            self._synced = True
        elif self._started is not None and now - self._started > self.ready_grace:
            # Peers unreachable past the grace window: a lone survivor
            # (or first replica of a rollout) must serve, not 503 forever.
            logger.warning(
                "state sync: no peer reachable after %.1fs; serving with "
                "local view only", self.ready_grace,
            )
            self._synced = True
        return self._synced

    async def sync_now(self) -> None:
        await self._sync_round()

    # -- membership --------------------------------------------------------

    def _live_peers(self, now: Optional[float] = None) -> Dict[str, _Peer]:
        now = now if now is not None else time.monotonic()
        return {
            rid: p for rid, p in self._peers.items()
            if now - p.seen <= self.peer_timeout
        }

    def live_replica_count(self) -> int:
        return 1 + len(self._live_peers())

    def admission_share(self) -> float:
        return 1.0 / self.live_replica_count()

    # -- structure views ---------------------------------------------------

    def remote_breaker_state(self, url: str) -> Optional[str]:
        worst: Optional[str] = None
        for peer in self._live_peers().values():
            state = peer.breakers.get(url)
            if state == "open":
                return "open"
            if state is not None:
                worst = state
        return worst

    def peer_request_stats(self) -> Dict[str, Dict[str, dict]]:
        return {rid: p.stats for rid, p in self._live_peers().items()}

    def peer_canary_ttfts(self) -> Dict[str, Dict[str, float]]:
        return {rid: p.canary for rid, p in self._live_peers().items()}

    def peer_fleet_snapshots(self) -> Dict[str, dict]:
        return {
            rid: p.fleet for rid, p in self._live_peers().items() if p.fleet
        }

    def merged_endpoint_urls(self, local: Sequence[str]) -> List[str]:
        merged = set(local)
        for peer in self._live_peers().values():
            merged |= peer.endpoints
        return sorted(merged)

    def publish_prefix_insert(self, path: Sequence[int], endpoint: str) -> None:
        self._prefix_seq += 1
        self._prefix_out.append((self._prefix_seq, list(path), endpoint))

    def drain_prefix_inserts(self) -> List[Tuple[List[int], str]]:
        out = list(self._prefix_in)
        self._prefix_in.clear()
        return out

    # -- journals ----------------------------------------------------------

    def checkpoint_journal(self, request_id: str, snapshot: dict) -> None:
        now = time.monotonic()
        entry = self._journals.get(request_id)
        if entry is None and self._local_journal_count() >= MAX_JOURNALS:
            return  # bounded: beyond the cap new streams lose HA, not service
        if entry is not None and entry.owner == self.replica_id():
            entry.snap = snapshot
            entry.ts = time.time()
            entry.seen = now
            return
        self._journals[request_id] = _Journal(
            self.replica_id(), snapshot, time.time(), now
        )

    def drop_journal(self, request_id: str) -> None:
        self._journals.pop(request_id, None)
        # Gossip the drop even without a local copy: a peer may hold one.
        self._drops.append((request_id, time.monotonic()))

    def claim_remote_journal(self, request_id: str) -> Optional[dict]:
        entry = self._journals.get(request_id)
        if entry is None or entry.owner == self.replica_id():
            return None
        owner = self._peers.get(entry.owner)
        if owner is not None and time.monotonic() - owner.seen <= self.peer_timeout:
            return None  # owner alive: it is still streaming this request
        # Claim-once: retire the checkpoint locally and fleet-wide so two
        # survivors cannot both splice the same suffix.
        self._journals.pop(request_id, None)
        self._drops.append((request_id, time.monotonic()))
        if time.monotonic() - entry.seen > self.journal_ttl:
            return {"stale": True}
        return {"snap": entry.snap}

    def _local_journal_count(self) -> int:
        me = self.replica_id()
        return sum(1 for j in self._journals.values() if j.owner == me)

    # -- the exchange ------------------------------------------------------

    def digest(self) -> dict:
        """This replica's gossip payload (also the server-side reply)."""
        me = self.replica_id()
        return {
            "replica": me,
            "ts": time.time(),
            "endpoints": list(self._provide(PROVIDER_ENDPOINTS, [])),
            "stats": self._provide(PROVIDER_REQUEST_STATS, {}),
            "breakers": self._provide(PROVIDER_BREAKERS, {}),
            "canary": self._provide(PROVIDER_CANARY_TTFT, {}),
            "fleet": self._provide(PROVIDER_FLEET_SNAPSHOT, {}),
            "prefix": [
                [seq, path, ep] for seq, path, ep in list(self._prefix_out)
            ],
            "journals": {
                rid: {"snap": j.snap, "ts": j.ts}
                for rid, j in self._journals.items()
                if j.owner == me
            },
            "drops": [rid for rid, _ in list(self._drops)],
        }

    def exchange(self, peer_digest: dict) -> dict:
        """Server side of one exchange: merge theirs, answer with ours."""
        self._apply(peer_digest)
        return self.digest()

    def _apply(self, digest: dict) -> bool:
        """Merge a peer digest; False when the digest is our own echo."""
        rid = digest.get("replica")
        if not isinstance(rid, str) or not rid or rid == self.replica_id():
            return False
        now = time.monotonic()
        peer = self._peers.get(rid)
        if peer is None:
            peer = _Peer()
            self._peers[rid] = peer
            logger.info("state sync: discovered replica %s", rid)
        peer.seen = now
        peer.endpoints = set(digest.get("endpoints") or [])
        stats = digest.get("stats")
        peer.stats = stats if isinstance(stats, dict) else {}
        breakers = digest.get("breakers")
        peer.breakers = breakers if isinstance(breakers, dict) else {}
        canary = digest.get("canary")
        peer.canary = canary if isinstance(canary, dict) else {}
        fleet = digest.get("fleet")
        peer.fleet = fleet if isinstance(fleet, dict) else {}
        # Prefix insertions: apply only sequence numbers we have not seen
        # from this replica (the out-queue is a sliding window, so digests
        # re-carry recent entries every round).
        last = self._applied_seq.get(rid, 0)
        newest = last
        for item in digest.get("prefix") or []:
            try:
                seq, path, ep = int(item[0]), list(item[1]), str(item[2])
            except (TypeError, ValueError, IndexError):
                continue
            if seq > last:
                self._prefix_in.append(([int(h) for h in path], ep))
                newest = max(newest, seq)
        self._applied_seq[rid] = newest
        # Journal checkpoints: freshest per request id wins; drops beat
        # checkpoints (a completed stream must never be resurrected).
        dropped = set(digest.get("drops") or [])
        for drid in dropped:
            self._journals.pop(drid, None)
        for jrid, entry in (digest.get("journals") or {}).items():
            if jrid in dropped or not isinstance(entry, dict):
                continue
            snap = entry.get("snap")
            if not isinstance(snap, dict):
                continue
            ts = float(entry.get("ts") or 0.0)
            known = self._journals.get(jrid)
            if known is None or (known.owner == rid and ts >= known.ts):
                self._journals[jrid] = _Journal(rid, snap, ts, now)
        return True

    # -- sync loop ---------------------------------------------------------

    async def _loop(self) -> None:
        while True:
            try:
                await self._sync_round()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — syncing is best-effort
                logger.error("state sync round failed: %s", e)
            await asyncio.sleep(self.sync_interval)

    async def _resolve_peers(self) -> List[Tuple[str, str]]:
        """Resolve peer specs to ``(label, base_url)`` pairs. ``label`` is
        the CONFIGURED spec (bounded set — the metrics label; resolved pod
        IPs churn on every rollout and would grow Prometheus cardinality
        without bound). ``dns://name:port`` resolves fresh every round
        (k8s headless service), explicit URLs pass through."""
        out: List[Tuple[str, str]] = []
        seen: set = set()
        loop = asyncio.get_running_loop()
        for spec in self.peer_specs:
            if spec.startswith("dns://"):
                parsed = urlparse(spec)
                host, port = parsed.hostname, parsed.port or 80
                try:
                    infos = await loop.getaddrinfo(host, port)
                except OSError as e:
                    logger.debug("peer DNS resolve failed for %s: %s", spec, e)
                    continue
                for info in infos:
                    addr = info[4][0]
                    # IPv6 addresses need brackets in URLs.
                    hostpart = f"[{addr}]" if ":" in addr else addr
                    url = f"http://{hostpart}:{port}"
                    if url not in seen:
                        seen.add(url)
                        out.append((spec, url))
            else:
                url = spec.rstrip("/")
                if url not in seen:
                    seen.add(url)
                    out.append((spec, url))
        return out

    def _targets_for(self, addrs: List[str]) -> Dict[str, _Target]:
        for addr in addrs:
            if addr not in self._targets:
                self._targets[addr] = _Target()
        return {a: self._targets[a] for a in addrs}

    async def _sync_round(self) -> None:
        if self._session is None:
            return
        resolved = await self._resolve_peers()
        targets = self._targets_for([url for _, url in resolved])
        # One digest per ROUND, not per peer: with journal checkpoints in
        # it, rebuilding+re-encoding per peer would be the expensive part.
        digest = self.digest()
        for label, addr in resolved:
            target = targets[addr]
            if target.is_self:
                continue
            await self._sync_with(label, addr, target, digest)
        self._prune()
        self._update_gauges()
        self._rounds += 1

    async def _sync_with(
        self, label: str, addr: str, target: _Target, digest: dict
    ) -> None:
        target.attempted = True
        headers = {}
        if self.api_key:
            headers["Authorization"] = f"Bearer {self.api_key}"
        t0 = time.monotonic()
        try:
            async with self._session.post(
                addr + GOSSIP_PATH, json=digest, headers=headers
            ) as resp:
                resp.raise_for_status()
                peer_digest = await resp.json()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — a dead peer is the signal
            metrics.sync_total.labels(peer=label, outcome="error").inc()
            logger.debug("state sync with %s failed: %s", addr, e)
            return
        metrics.sync_seconds.observe(time.monotonic() - t0)
        if not self._apply(peer_digest):
            if peer_digest.get("replica") == self.replica_id():
                # DNS handed us our own address (headless service lists
                # every pod): remember and stop dialing ourselves.
                target.is_self = True
                metrics.sync_total.labels(peer=label, outcome="self").inc()
                return
            metrics.sync_total.labels(peer=label, outcome="invalid").inc()
            return
        target.succeeded = True
        metrics.sync_total.labels(peer=label, outcome="ok").inc()

    def _prune(self) -> None:
        now = time.monotonic()
        # Journals past TTL are unusable for splicing — retire them.
        for rid in [
            r for r, j in self._journals.items()
            if now - j.seen > self.journal_ttl * 2
        ]:
            self._journals.pop(rid, None)
        while self._drops and now - self._drops[0][1] > 30.0:
            self._drops.popleft()
        # Peers dead for a long time (10x timeout) are forgotten entirely
        # so a churned fleet does not grow the table without bound.
        for rid in [
            r for r, p in self._peers.items()
            if now - p.seen > self.peer_timeout * 10
        ]:
            self._peers.pop(rid, None)
            self._applied_seq.pop(rid, None)

    def _update_gauges(self) -> None:
        me = self.replica_id()
        local = sum(1 for j in self._journals.values() if j.owner == me)
        metrics.replica_peers.set(self.live_replica_count())
        metrics.admission_share.set(self.admission_share())
        metrics.journals.labels(kind="local").set(local)
        metrics.journals.labels(kind="remote").set(len(self._journals) - local)

    # -- introspection -----------------------------------------------------

    def describe(self) -> dict:
        base = super().describe()
        base.update({
            "peers": {
                rid: round(time.monotonic() - p.seen, 2)
                for rid, p in self._peers.items()
            },
            "admission_share": self.admission_share(),
            "journals": len(self._journals),
        })
        return base
