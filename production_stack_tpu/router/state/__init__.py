"""Router state package: the replicated-state layer behind router HA.

- :mod:`base` — the :class:`StateBackend` interface; its defaults ARE the
  single-replica semantics.
- :mod:`memory` — the default in-memory backend (zero behavior change).
- :mod:`gossip` — gossip-over-HTTP replication so N router replicas
  behave as one (docs/router-ha.md).
- :mod:`metrics` — the ``pst_router_replica_*`` Prometheus surface.

Lifecycle mirrors the other router singletons (initialize/get/teardown).
``get_state_backend()`` returns ``None`` before initialization so every
consumer degrades to pre-HA behavior — the same contract as the
resilience accessors.
"""

from __future__ import annotations

from typing import Optional

from ...utils import parse_comma_separated
from .base import (
    PROVIDER_BREAKERS,
    PROVIDER_CANARY_TTFT,
    PROVIDER_ENDPOINTS,
    PROVIDER_FLEET_SNAPSHOT,
    PROVIDER_REQUEST_STATS,
    StateBackend,
)
from .gossip import GOSSIP_PATH, GossipStateBackend
from .memory import InMemoryStateBackend

# App-scoped (router.appscope): each router app owns its backend; the
# old module global was last-app-wins across replicas in one process.
_SCOPE_KEY = "state_backend"


def initialize_state_backend(args) -> StateBackend:
    """Create the backend from parsed router args (pre-event-loop; the
    gossip loop starts with ``await backend.start()`` in on_startup)."""
    from .. import appscope

    kind = getattr(args, "state_backend", "memory") or "memory"
    backend: StateBackend
    if kind == "gossip":
        backend = GossipStateBackend(
            peers=parse_comma_separated(getattr(args, "state_peers", None)),
            replica_id=getattr(args, "state_replica_id", None) or None,
            sync_interval=getattr(args, "state_sync_interval", 0.5),
            peer_timeout=getattr(args, "state_peer_timeout", 3.0),
            api_key=getattr(args, "api_key", None),
        )
    else:
        backend = InMemoryStateBackend(
            replica_id=getattr(args, "state_replica_id", None) or None
        )
    return appscope.scoped_set(_SCOPE_KEY, backend)


def get_state_backend() -> Optional[StateBackend]:
    from .. import appscope

    return appscope.scoped_get(_SCOPE_KEY)


def teardown_state_backend() -> None:
    from .. import appscope

    appscope.scoped_set(_SCOPE_KEY, None)


__all__ = [
    "GOSSIP_PATH",
    "GossipStateBackend",
    "InMemoryStateBackend",
    "PROVIDER_BREAKERS",
    "PROVIDER_CANARY_TTFT",
    "PROVIDER_ENDPOINTS",
    "PROVIDER_FLEET_SNAPSHOT",
    "PROVIDER_REQUEST_STATS",
    "StateBackend",
    "get_state_backend",
    "initialize_state_backend",
    "teardown_state_backend",
]
