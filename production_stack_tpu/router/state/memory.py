"""The default single-replica backend: the base class, under its own name.

Kept as a distinct class (rather than using :class:`~.base.StateBackend`
directly) so logs, ``/ready`` payloads, and tests name the configured
backend explicitly, and so future local-only optimizations have a home
that is unmistakably not the interface definition.
"""

from __future__ import annotations

from .base import StateBackend


class InMemoryStateBackend(StateBackend):
    name = "memory"
    shared = False
