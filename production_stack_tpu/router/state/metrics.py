"""Prometheus surface for router replication (``pst_router_replica_*``).

Declared in ``obs/metric_registry.py`` and documented in
docs/observability.md ("Router HA / replication" rows); the
``metric-registry`` pstlint check enforces the triangle.
"""

from prometheus_client import Counter, Gauge, Histogram

replica_peers = Gauge(
    "pst_router_replica_peers",
    "Live router replicas in the shared-state membership view (self "
    "included; 1 = single replica or every peer dead)",
)
sync_total = Counter(
    "pst_router_replica_sync",
    "State-sync (gossip) exchanges attempted, by peer address and outcome",
    ["peer", "outcome"],
)
sync_seconds = Histogram(
    "pst_router_replica_sync_seconds",
    "Wall time of one state-sync exchange with one peer",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5),
)
admission_share = Gauge(
    "pst_router_replica_admission_share",
    "Fraction of the global admission rate this replica currently admits "
    "(1/live-replicas under rate splitting)",
)
journals = Gauge(
    "pst_router_replica_journals",
    "Stream-resume journal checkpoints held, by kind (local = owned by "
    "this replica, remote = checkpointed here for takeover)",
    ["kind"],
)
takeovers_total = Counter(
    "pst_router_replica_takeovers",
    "Journaled streams claimed from a dead replica, by outcome (resumed = "
    "continuation spliced, stale = checkpoint unusable, visible truncation)",
    ["outcome"],
)
