"""Capacity signals: the autoscaler input ROADMAP item 3 needs, computed
in-process.

``GET /autoscale/signal`` (docs/observability.md "Capacity signals")
answers "how many replicas does this load actually need?" from the three
signals the reference stack punts to external Prometheus rules:

- **Multi-window SLO burn rate** — the PR 5 windows (5m/30m/1h/6h/3d)
  and thresholds (page at 14.4x the error budget, ticket at 1x; mirrored
  from ``observability/gen_dashboards.py``), computed over the SAME SLO
  events ``pst_slo_*`` counts (``metrics_service.observe_slo_ttft``
  feeds both), so the in-process rates and the Prometheus recorded
  series describe one reality.
- **Admission-queue depth + slope** — depth from the admission
  controller, slope from a bounded sample ring: a rising queue at
  constant offered load is the earliest saturation signal, well before
  TTFT degrades.
- **Fleet KV / compute headroom** — from the gossip-merged
  ``/debug/fleet`` snapshot (PR 13), so every router replica serves the
  same signal modulo one sync interval and KEDA can scrape any of them.

The JSON is deliberately scaler-agnostic: ``saturation`` (0..1),
per-window ``burn_rates``, ``replica_hint`` (an absolute engine-count
suggestion) — consumable today by KEDA's ``metrics-api`` scaler
(docs/tutorials/21-keda-deep-dive.md) without a Prometheus in the loop.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

from prometheus_client import Gauge

from ...logging_utils import init_logger
from .. import appscope

logger = init_logger(__name__)

# The PR 5 SLO window set (observability/gen_dashboards.py and the
# generated prometheus-rules.yaml use the same constants; the SRE-workbook
# multi-window multi-burn-rate shape). Seconds per window label.
BURN_WINDOWS: Tuple[Tuple[str, int], ...] = (
    ("5m", 300),
    ("30m", 1800),
    ("1h", 3600),
    ("6h", 21600),
    ("3d", 259200),
)
# Mirrors gen_dashboards.SLO_OBJECTIVE (asserted equal in
# tests/test_flight_cost.py so the two cannot drift).
SLO_OBJECTIVE = 0.99
SLO_ERROR_BUDGET = round(1.0 - SLO_OBJECTIVE, 6)
# Burn-rate thresholds (multiples of the error budget): page = budget
# gone in ~2 days, ticket = budget gone in 30 days.
PAGE_BURN_RATE = 14.4
TICKET_BURN_RATE = 1.0
# The page alert fires on 1h AND 5m; in-process the short window is the
# actionable one for scale-up (an autoscaler reacting on the 1h window
# alone would be an hour late).
_FAST_WINDOW = "5m"
_SLOW_WINDOW = "1h"

# Event-ring granularity: second-resolution buckets would hold 259200
# entries for the 3d window; 30 s buckets keep it bounded (~8640) with
# no visible loss at autoscaler timescales.
_BUCKET_S = 30

saturation_gauge = Gauge(
    "pst_capacity_saturation",
    "Composite fleet saturation in [0, 1]: max of KV occupancy, "
    "normalized admission-queue pressure and normalized fast-window SLO "
    "burn (1.0 = scale up now)",
)
burn_rate_gauge = Gauge(
    "pst_capacity_burn_rate",
    "Multi-window TTFT-SLO burn rate (error ratio over the error "
    "budget), computed in-process over the same events pst_slo_* counts",
    ["window"],
)
replica_hint_gauge = Gauge(
    "pst_capacity_replica_hint",
    "Suggested ready-engine count from burn rate + queue slope + "
    "headroom — the /autoscale/signal scrape target for KEDA",
)
queue_slope_gauge = Gauge(
    "pst_capacity_queue_depth_slope",
    "Admission-queue depth slope (requests/second) over the sample "
    "window — rising queue at constant load is the earliest saturation "
    "signal",
)
kv_headroom_gauge = Gauge(
    "pst_capacity_kv_headroom",
    "Mean free-KV fraction across ready engines (gossip-merged view): "
    "1.0 = empty fleet, 0.0 = every engine's pages are full",
)


class CapacityMonitor:
    """In-process SLO-event windows + queue-depth samples → one signal.

    Thread-safe: SLO events arrive from request handlers on the event
    loop, but ``/metrics`` and tests may touch it from other threads;
    the critical sections are tiny."""

    _QUEUE_SAMPLES = 240  # bounded (t, depth) ring for the slope fit

    def __init__(self, slo_objective: float = SLO_OBJECTIVE):
        self.error_budget = max(1.0 - float(slo_objective), 1e-6)
        self._lock = threading.Lock()
        # bucket_start_ts -> [total, within]; trimmed past the longest
        # window so memory is bounded by 3d / _BUCKET_S entries.
        self._buckets: "Dict[int, list]" = {}
        self._horizon = max(s for _, s in BURN_WINDOWS)
        self._queue_samples: "deque[Tuple[float, int]]" = deque(
            maxlen=self._QUEUE_SAMPLES
        )

    # -- event feeds -----------------------------------------------------

    def observe(self, within: bool, now: Optional[float] = None) -> None:
        """One SLO-counted request (the same event pst_slo_requests
        counts): ``within`` = TTFT met the target."""
        now = now if now is not None else time.time()
        key = int(now // _BUCKET_S) * _BUCKET_S
        with self._lock:
            b = self._buckets.get(key)
            if b is None:
                b = self._buckets[key] = [0, 0]
                self._trim_locked(now)
            b[0] += 1
            if within:
                b[1] += 1

    def sample_queue_depth(self, depth: int, now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()
        with self._lock:
            # One sample per second at most: signal() polls can be rapid.
            if self._queue_samples and now - self._queue_samples[-1][0] < 1.0:
                self._queue_samples[-1] = (now, int(depth))
            else:
                self._queue_samples.append((now, int(depth)))

    def _trim_locked(self, now: float) -> None:
        cutoff = now - self._horizon - _BUCKET_S
        for key in [k for k in self._buckets if k < cutoff]:
            del self._buckets[key]

    # -- derived signals -------------------------------------------------

    def burn_rates(self, now: Optional[float] = None) -> Dict[str, float]:
        """Per-window error-ratio / error-budget, 0.0 when the window saw
        no traffic (no requests = no budget burned)."""
        now = now if now is not None else time.time()
        with self._lock:
            items = list(self._buckets.items())
        out: Dict[str, float] = {}
        for label, seconds in BURN_WINDOWS:
            cutoff = now - seconds
            total = within = 0
            for key, (t, w) in items:
                if key >= cutoff - _BUCKET_S:
                    total += t
                    within += w
            if total <= 0:
                out[label] = 0.0
            else:
                error_ratio = (total - within) / total
                out[label] = round(error_ratio / self.error_budget, 4)
        return out

    def queue_slope(self) -> float:
        """Least-squares depth slope (requests/second) over the retained
        samples; 0 with fewer than 3 samples or a degenerate time span."""
        with self._lock:
            pts = list(self._queue_samples)
        if len(pts) < 3:
            return 0.0
        t0 = pts[0][0]
        xs = [t - t0 for t, _ in pts]
        ys = [d for _, d in pts]
        n = len(pts)
        sx, sy = sum(xs), sum(ys)
        sxx = sum(x * x for x in xs)
        sxy = sum(x * y for x, y in zip(xs, ys))
        denom = n * sxx - sx * sx
        if denom <= 1e-9:
            return 0.0
        return round((n * sxy - sx * sy) / denom, 4)

    def reset_for_tests(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._queue_samples.clear()


def _peer_capacity_evidence(app) -> list:
    """Peers' gossiped capacity evidence (the ``capacity`` section each
    replica publishes inside its fleet snapshot, ``services/fleet.py``).
    Empty when gossip is off or no peer has synced yet."""
    from ..state import get_state_backend

    backend = app.get("state_backend") if app is not None else None
    if backend is None:
        try:
            backend = get_state_backend()
        except Exception:  # noqa: BLE001 — no backend (tests, static)
            return []
    if backend is None:
        return []
    try:
        peers = backend.peer_fleet_snapshots() or {}
    except Exception:  # noqa: BLE001 — backend without snapshot support
        return []
    out = []
    for view in peers.values():
        cap = view.get("capacity") if isinstance(view, dict) else None
        if isinstance(cap, dict):
            out.append(cap)
    return out


def _fleet_view(app) -> dict:
    """Ready-engine count + KV statistics from the gossip-merged fleet
    snapshot (every replica computes the same numbers modulo one sync
    interval)."""
    from .fleet import merged_fleet_snapshot

    merged = merged_fleet_snapshot(app)
    ready = 0
    occupancies = []
    in_flight = 0
    for e in (merged.get("engines") or {}).values():
        if not isinstance(e, dict):
            continue
        if e.get("state") == "ready" and e.get("breaker") != "open":
            ready += 1
            occ = e.get("kv_occupancy")
            if isinstance(occ, (int, float)):
                occupancies.append(min(max(float(occ), 0.0), 1.0))
        in_flight += int(e.get("in_flight_total") or e.get("in_flight") or 0)
    kv_mean = sum(occupancies) / len(occupancies) if occupancies else 0.0
    kv_max = max(occupancies) if occupancies else 0.0
    return {
        "engines_total": len(merged.get("engines") or {}),
        "engines_ready": ready,
        "kv_occupancy_mean": round(kv_mean, 4),
        "kv_occupancy_max": round(kv_max, 4),
        "kv_headroom": round(1.0 - kv_mean, 4),
        "in_flight_total": in_flight,
        "replicas": len(merged.get("replicas") or {}) or 1,
    }


def compute_signal(monitor: CapacityMonitor, app=None) -> dict:
    """The ``GET /autoscale/signal`` payload (and the pst_capacity_*
    gauge refresh). Pure derivation — no I/O beyond the in-memory gossip
    view, so scraping it is as cheap as /metrics."""
    from ...resilience import get_admission_controller

    now = time.time()
    burn = monitor.burn_rates(now)
    controller = None
    try:
        controller = get_admission_controller()
    except Exception:  # noqa: BLE001 — resilience not initialized (tests)
        controller = None
    queue_depth = 0
    queue_capacity = 0
    if controller is not None and getattr(controller, "enabled", False):
        queue_depth = controller.queue_len()
        queue_capacity = int(getattr(controller, "max_queue", 0) or 0)
    monitor.sample_queue_depth(queue_depth, now)
    slope = monitor.queue_slope()
    fleet = _fleet_view(app)

    # Merge peers' gossiped capacity evidence so every replica derives
    # the hint from the FLEET's burn/queue reality, not just its own
    # routed share: burn rates take the per-window max (one replica
    # paging means the fleet is paging), queue depth/capacity and slope
    # sum (each replica queues only its own admissions). Two gossiping
    # replicas therefore serve the same replica_hint within one sync
    # interval — the agreement contract tests/test_flight_cost.py pins.
    peer_evidence = _peer_capacity_evidence(app)
    for cap in peer_evidence:
        peer_burn = cap.get("burn_rates") or {}
        for label in list(burn):
            try:
                burn[label] = round(
                    max(burn[label], float(peer_burn.get(label) or 0.0)), 4
                )
            except (TypeError, ValueError):
                continue
        try:
            queue_depth += int(cap.get("queue_depth") or 0)
            queue_capacity += int(cap.get("queue_capacity") or 0)
            slope = round(
                slope + float(cap.get("queue_depth_slope_per_s") or 0.0), 4
            )
        except (TypeError, ValueError):
            continue

    fast_burn = burn.get(_FAST_WINDOW, 0.0)
    slow_burn = burn.get(_SLOW_WINDOW, 0.0)
    queue_pressure = (
        min(queue_depth / queue_capacity, 1.0) if queue_capacity > 0 else 0.0
    )
    saturation = round(
        max(
            fleet["kv_occupancy_max"],
            queue_pressure,
            min(fast_burn / PAGE_BURN_RATE, 1.0),
        ),
        4,
    )

    # Replica hint: an ABSOLUTE ready-engine suggestion, monotone in the
    # burn/queue evidence. Conservative on scale-down (only when the
    # fleet is provably idle) — flapping replicas cost warmup time.
    current = max(fleet["engines_ready"], 1)
    # The SRE-workbook multi-window rule the generated alert encodes:
    # page only when the fast AND slow windows both burn past threshold
    # (the 1h window is a superset of the 5m one, so a genuine page-rate
    # burn reaches both quickly; a diluted 1h rate correctly vetoes).
    page_burning = (
        fast_burn >= PAGE_BURN_RATE and slow_burn >= PAGE_BURN_RATE
    )
    if page_burning:
        # Budget gone in ~2 days at this rate: grow by half the fleet
        # (at least one), same spirit as HPA's proportional response.
        hint = current + max(1, math.ceil(current * 0.5))
    elif fast_burn >= TICKET_BURN_RATE and slope > 0:
        hint = current + 1
    elif queue_pressure >= 0.5 or (slope > 0 and queue_depth > 2 * current):
        hint = current + 1
    elif (
        saturation < 0.25
        and fast_burn < TICKET_BURN_RATE
        and slope <= 0
        and fleet["engines_ready"] > 1
    ):
        hint = current - 1
    else:
        hint = current

    signal = {
        "ts": now,
        "slo_objective": SLO_OBJECTIVE,
        "error_budget": SLO_ERROR_BUDGET,
        "burn_rates": burn,
        "page_burn_rate": PAGE_BURN_RATE,
        "ticket_burn_rate": TICKET_BURN_RATE,
        "page_burning": bool(page_burning),
        "queue_depth": queue_depth,
        "queue_capacity": queue_capacity,
        "queue_depth_slope_per_s": slope,
        "saturation": saturation,
        "replica_hint": hint,
        # How many replicas' evidence (self + synced peers) fed this
        # derivation — 1 means a purely local view.
        "evidence_replicas": 1 + len(peer_evidence),
        **fleet,
    }
    # Gauge twins so a plain Prometheus pipeline (or the dashboards' new
    # Capacity row) sees the same numbers the JSON serves.
    saturation_gauge.set(saturation)
    for window, rate in burn.items():
        burn_rate_gauge.labels(window=window).set(rate)
    replica_hint_gauge.set(hint)
    queue_slope_gauge.set(slope)
    kv_headroom_gauge.set(fleet["kv_headroom"])
    return signal


# -- app-scoped lifecycle (router/appscope.py) ---------------------------

_SCOPE_KEY = "capacity_monitor"


def initialize_capacity_monitor(enabled: bool = True) -> Optional[CapacityMonitor]:
    monitor = CapacityMonitor() if enabled else None
    appscope.scoped_set(_SCOPE_KEY, monitor)
    return monitor


def get_capacity_monitor() -> Optional[CapacityMonitor]:
    return appscope.scoped_get(_SCOPE_KEY)
