"""Fleet introspection snapshot — the read-side of the whole deployment.

``GET /debug/fleet`` (docs/observability.md "Fleet debugging") answers
with ONE gossip-merged JSON picture of a deployment: replica membership
with sync ages, per-engine state (health phase, breaker, routed
in-flight, KV occupancy, canary TTFT, compile counters from the
scraper), the fleet-routing view (session pins, trie size, spill/remap
totals), and per-tenant DRR credit/queue/shed state. Before this module
an operator hand-joined ``/metrics`` + ``/engines`` + ``/debug/requests``
across every router and engine pod.

Mechanics: each replica builds :func:`local_fleet_snapshot` from its own
app-scoped services; the snapshot rides the ``fleet_snapshot`` gossip
digest key through the existing :class:`StateBackend`
(``router/state``), so every replica holds every peer's latest view and
:func:`merged_fleet_snapshot` renders the same deployment picture from
any replica, modulo one sync interval. ``pst-top``
(``python -m production_stack_tpu.obs.top``) is the terminal client.

Merge policy per structure: engine fields take the freshest replica's
view (each snapshot is stamped), routed in-flight sums across replicas
(each replica counts only its own proxied traffic), tenant queue depths
and admitted/shed totals sum, and routing tables stay per-replica (pins
are replica-local state, summing them would be a lie).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from prometheus_client import Gauge

from ...logging_utils import init_logger

logger = init_logger(__name__)

# Engines per health phase in this replica's discovery view, refreshed at
# scrape time (GET /metrics) — the alert-friendly scalar twin of the
# /debug/fleet JSON.
fleet_engines = Gauge(
    "pst_fleet_engines",
    "Engines in the fleet by health phase (ready|warming|draining|sleeping)",
    ["state"],
)


def _engine_phase(ep: Any) -> str:
    if getattr(ep, "sleep", False):
        return "sleeping"
    if getattr(ep, "draining", False):
        return "draining"
    if getattr(ep, "warming", False):
        return "warming"
    return "ready"


def refresh_fleet_gauges(endpoints) -> None:
    counts = {"ready": 0, "warming": 0, "draining": 0, "sleeping": 0}
    for ep in endpoints:
        counts[_engine_phase(ep)] += 1
    for state, n in counts.items():
        fleet_engines.labels(state=state).set(n)


def _resolve(app, key: str, getter) -> Any:
    """App-injected instance first (the gossip provider runs outside any
    request context), ambient scope lookup second, None when neither
    resolves — a snapshot must degrade, never raise."""
    if app is not None:
        inst = app.get(key)
        if inst is not None:
            return inst
    try:
        return getter()
    except Exception:  # noqa: BLE001 — absent service = absent section
        return None


def local_fleet_snapshot(app=None, compact: bool = False) -> dict:
    """THIS replica's contribution to the fleet picture.

    ``compact=True`` is the gossip-provider variant: it drops the
    per-engine score/load maps from the routing view — they are
    redundant with the ``loads`` digest key already gossiped for fleet
    scoring, and the digest ships twice per second whether or not
    anyone reads ``/debug/fleet``, so the replicated payload carries
    only what no other key does (scraper warm-state, pins/trie/spill
    totals, tenant DRR state)."""
    from ...resilience import get_admission_controller, get_breaker_registry
    from ..routing.logic import get_routing_logic
    from ..service_discovery import get_service_discovery
    from ..state import get_state_backend
    from ..stats.engine_stats import get_engine_stats_scraper
    from ..stats.request_stats import get_request_stats_monitor
    from .canary import get_canary_prober
    from .capacity import get_capacity_monitor

    backend = _resolve(app, "state_backend", get_state_backend)
    discovery = _resolve(app, "service_discovery", get_service_discovery)
    scraper = _resolve(app, "engine_stats_scraper", get_engine_stats_scraper)
    monitor = _resolve(app, "request_stats_monitor", get_request_stats_monitor)
    prober = _resolve(app, "canary_prober", get_canary_prober)
    router = _resolve(app, "routing_logic", get_routing_logic)
    try:
        breakers = get_breaker_registry()
    except Exception:  # noqa: BLE001
        breakers = None
    try:
        controller = get_admission_controller()
    except Exception:  # noqa: BLE001
        controller = None

    engine_stats = scraper.get_engine_stats() if scraper is not None else {}
    # LOCAL routed in-flight only: the merge sums per-replica counts, so
    # publishing the fleet-merged view would double-count peers' traffic.
    request_stats = (
        monitor.get_request_stats(time.time(), fleet=False)
        if monitor is not None else {}
    )
    canary = prober.ttft_view() if prober is not None else {}

    engines: Dict[str, dict] = {}
    for ep in (discovery.get_endpoint_info() if discovery is not None else []):
        url = ep.url
        es = engine_stats.get(url)
        rs = request_stats.get(url)
        entry: Dict[str, Any] = {
            "id": ep.Id,
            "models": list(ep.model_names),
            "model_label": ep.model_label,
            "state": _engine_phase(ep),
            "breaker": (
                breakers.state(url).value if breakers is not None else None
            ),
            "in_flight": (
                rs.in_prefill_requests + rs.in_decoding_requests
                if rs is not None else 0
            ),
            "canary_ttft_s": canary.get(url),
        }
        if es is not None:
            entry.update({
                "running": es.num_running_requests,
                "waiting": es.num_queuing_requests,
                "kv_occupancy": (
                    es.engine_kv_page_occupancy
                    or es.gpu_cache_usage_perc
                ),
                "prefix_hit_rate": es.gpu_prefix_cache_hit_rate,
                "compiles_total": es.engine_compiles_total,
                "host_gap_p50_s": getattr(es, "engine_host_gap_p50", 0.0),
                "warmup_coverage": getattr(es, "engine_warmup_coverage", 0.0),
            })
        engines[url] = entry

    routing = router.describe() if router is not None else {}
    if compact:
        routing = {
            k: v for k, v in routing.items()
            if k not in ("last_scores", "last_loads")
        }
    snapshot: Dict[str, Any] = {
        "replica": backend.replica_id() if backend is not None else "local",
        "ts": time.time(),
        "engines": engines,
        "routing": routing,
        "tenants": (
            controller.tenants_snapshot() if controller is not None else {}
        ),
    }

    # Capacity evidence (docs/autoscaling.md "Signal convergence"): the
    # SLO-burn windows and admission-queue state feeding
    # /autoscale/signal are replica-LOCAL — only the replica that
    # proxied a slow request burns budget for it. Gossiping the raw
    # evidence lets every replica's compute_signal() merge the fleet's
    # view (burn = max, queue = sum) so two routers serve the SAME
    # replica_hint within one sync interval — the convergence the
    # operator's max-merge relies on as defense, not correctness.
    cap_monitor = _resolve(app, "capacity_monitor", get_capacity_monitor)
    if cap_monitor is not None:
        queue_depth = queue_capacity = 0
        if controller is not None and getattr(controller, "enabled", False):
            queue_depth = controller.queue_len()
            queue_capacity = int(getattr(controller, "max_queue", 0) or 0)
        snapshot["capacity"] = {
            "burn_rates": cap_monitor.burn_rates(),
            "queue_depth": queue_depth,
            "queue_capacity": queue_capacity,
            "queue_depth_slope_per_s": cap_monitor.queue_slope(),
        }
    return snapshot


def _merge_tenants(
    merged: Dict[str, dict], view: Dict[str, dict], rid: str
) -> None:
    for name, t in (view or {}).items():
        if not isinstance(t, dict):
            continue
        cur = merged.setdefault(name, {
            "tier": t.get("tier"),
            "weight": t.get("weight"),
            "queue_depth": 0,
            "admitted_total": 0,
            "sheds_total": 0,
        })
        cur["tier"] = t.get("tier", cur.get("tier"))
        cur["weight"] = t.get("weight", cur.get("weight"))
        for key in ("queue_depth", "admitted_total", "sheds_total"):
            try:
                cur[key] = cur.get(key, 0) + int(t.get(key) or 0)
            except (TypeError, ValueError):
                continue
        if "drr_deficit" in t:
            cur.setdefault("drr_deficit_by_replica", {})[rid] = t[
                "drr_deficit"
            ]


def merged_fleet_snapshot(app=None) -> dict:
    """The gossip-merged deployment picture every replica serves.

    Identical modulo sync lag: each replica merges its own local view
    with every live peer's gossiped snapshot; per-engine fields follow
    the freshest stamp, routed in-flight and tenant counters sum, and
    routing tables key by owning replica.
    """
    from ..state import get_state_backend

    backend = _resolve(app, "state_backend", get_state_backend)
    local = local_fleet_snapshot(app)
    peers = (
        backend.peer_fleet_snapshots() if backend is not None else {}
    )

    views = [local] + [
        v for v in peers.values() if isinstance(v, dict)
    ]
    # Oldest first so newer views overwrite per-engine fields.
    views.sort(key=lambda v: float(v.get("ts") or 0.0))

    engines: Dict[str, dict] = {}
    tenants: Dict[str, dict] = {}
    routing: Dict[str, dict] = {}
    for view in views:
        rid = str(view.get("replica") or "unknown")
        for url, e in (view.get("engines") or {}).items():
            if not isinstance(e, dict):
                continue
            cur = engines.setdefault(url, {"in_flight_by_replica": {}})
            by_replica = cur["in_flight_by_replica"]
            by_replica[rid] = int(e.get("in_flight") or 0)
            cur.update({k: v for k, v in e.items() if k != "in_flight"})
            cur["in_flight_by_replica"] = by_replica
        _merge_tenants(tenants, view.get("tenants") or {}, rid)
        if view.get("routing"):
            routing[rid] = view["routing"]
    for e in engines.values():
        e["in_flight_total"] = sum(e["in_flight_by_replica"].values())

    replicas: Dict[str, dict] = {
        str(local["replica"]): {"self": True, "sync_age_s": 0.0}
    }
    if backend is not None:
        ages = (backend.describe() or {}).get("peers") or {}
        for rid in peers:
            replicas[str(rid)] = {
                "self": False,
                "sync_age_s": ages.get(rid),
            }

    return {
        "replica": local["replica"],
        "ts": local["ts"],
        "synced": backend.synced() if backend is not None else True,
        "replicas": replicas,
        "engines": engines,
        "routing": routing,
        "tenants": tenants,
    }


def fleet_snapshot_provider(app) -> "Any":
    """The ``fleet_snapshot`` gossip provider for ``app`` — a closure so
    the gossip loop (no request context) still snapshots THIS app's
    services, not whichever app initialized last."""
    def provide() -> Optional[dict]:
        return local_fleet_snapshot(app, compact=True)

    return provide
