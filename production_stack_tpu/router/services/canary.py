"""Canary prober: synthetic per-engine TTFT, feeding the SLO/health view.

A lightweight asyncio task (``--canary-interval``, 0 = off) sends one tiny
streamed completion (``max_tokens=1``) to every discovered engine each
interval and measures the time to the first SSE byte — the same signal a
real request's TTFT rides, but emitted even when the engine is idle, so a
cold decode path, a pending recompile, or a half-dead engine shows up in
``pst_canary_ttft_seconds{engine}`` *before* a user request pays for it.

Probe outcomes feed the existing breaker/health view: a successful probe
records breaker success (it IS a live probe — exactly what a half-open
breaker wants), a hard failure (connect error / 5xx) records breaker
failure and increments ``pst_canary_failures_total``. Deliberate drain
rejections, sleeping engines, and warming (precompiling) engines are
skipped, not failed.
"""

# pstlint: disable-file=hop-contract(canary probes ORIGINATE synthetic traffic — there is no client request whose deadline/trace/request-id could be propagated; probes are marked X-PST-Canary instead)
from __future__ import annotations

import asyncio
import time
from typing import Optional

import aiohttp

from ...logging_utils import init_logger
from ...obs.tasks import spawn_owned
from ...resilience import get_breaker_registry
from ..service_discovery import get_service_discovery
from . import metrics_service as gauges

logger = init_logger(__name__)

# Marks probe traffic so engines/operators can tell it from user load.
CANARY_HEADER = "X-PST-Canary"


class CanaryProber:
    def __init__(
        self,
        interval: float,
        timeout: float = 5.0,
        prompt: str = "ping",
        api_key: Optional[str] = None,
    ):
        self.interval = interval
        self.timeout = timeout
        self.prompt = prompt
        # The fleet shares one api key (helm apiKeySecret wires the same
        # secret into engines and router): probes must authenticate like
        # real traffic or every probe on a protected fleet would 401.
        self.api_key = api_key
        self._task: Optional[asyncio.Task] = None
        self._session: Optional[aiohttp.ClientSession] = None
        # Probes completed / failed (tests + /health introspection).
        self.probes_total = 0
        self.failures_total = 0
        # Last observed canary TTFT per engine URL — the health input
        # fleet routing multiplies into its score (a failed probe records
        # the probe timeout: "as slow as we ever waited"). Readers get a
        # copy via ttft_view(); engines that leave the fleet are dropped
        # via evict() (a departed fast engine must not skew the
        # fleet-best reference forever).
        # pstlint: owned-by=task:_probe_one,evict
        self.last_ttft: dict = {}

    @property
    def enabled(self) -> bool:
        return self.interval > 0

    def ttft_view(self) -> dict:
        """Copy of the last canary TTFT per engine (seconds). Engines the
        prober has not reached yet are absent — scoring treats them as
        healthy rather than punishing the unprobed."""
        return dict(self.last_ttft)

    def evict(self, url: str) -> None:
        """An engine left the fleet: forget its sample, or pod churn
        grows the table without bound and a departed fast engine skews
        the relative-health baseline for every survivor."""
        self.last_ttft.pop(url, None)

    async def start(self) -> None:
        if not self.enabled or self._task is not None:
            return
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.timeout)
        )
        self._task = spawn_owned(self._loop(), name="canary-prober")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def _loop(self) -> None:
        while True:
            try:
                endpoints = get_service_discovery().get_endpoint_info()
                await asyncio.gather(
                    *(self._probe_one(ep) for ep in endpoints)
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — probing is best-effort
                logger.debug("canary sweep failed: %s", e)
            await asyncio.sleep(self.interval)

    async def _probe_one(self, ep) -> None:
        if (
            getattr(ep, "sleep", False)
            or getattr(ep, "draining", False)
            or getattr(ep, "warming", False)
        ):
            # Warming engines are skipped, not failed: a probe would queue
            # behind the precompile pass and feed the breaker a spurious
            # failure for a deliberate state.
            return
        model = ep.model_names[0] if ep.model_names else ""
        body = {
            "model": model,
            "prompt": self.prompt,
            "max_tokens": 1,
            "temperature": 0.0,
            "stream": True,
        }
        registry = get_breaker_registry()
        headers = {CANARY_HEADER: "1"}
        if self.api_key:
            headers["Authorization"] = f"Bearer {self.api_key}"
        t0 = time.monotonic()
        try:
            async with self._session.post(
                f"{ep.url}/v1/completions",
                json=body,
                headers=headers,
            ) as resp:
                if resp.status == 503 and "X-PST-Draining" in resp.headers:
                    return  # deliberate drain rejection: not a failure
                if resp.status >= 400:
                    # Any error is a failed probe (a 401/404 error body's
                    # latency is NOT a TTFT sample), but only 5xx feeds
                    # the breaker: a misconfigured probe (bad key, model
                    # name mismatch) must never close an OPEN breaker via
                    # record_success nor open a healthy engine's breaker.
                    self.failures_total += 1
                    gauges.canary_failures_total.labels(engine=ep.url).inc()
                    if registry is not None and resp.status >= 500:
                        registry.record_failure(ep.url)
                    logger.debug(
                        "canary probe got %d from %s", resp.status, ep.url
                    )
                    return
                # Time-to-first-byte is the probe's TTFT; drain the rest so
                # the connection returns to the pool cleanly.
                ttft = None
                async for _ in resp.content.iter_any():
                    if ttft is None:
                        ttft = time.monotonic() - t0
                if ttft is None:
                    ttft = time.monotonic() - t0
            gauges.canary_ttft_seconds.labels(engine=ep.url).set(ttft)
            self.last_ttft[ep.url] = ttft
            self.probes_total += 1
            if registry is not None:
                registry.record_success(ep.url)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — a dead engine is the signal
            self.failures_total += 1
            gauges.canary_failures_total.labels(engine=ep.url).inc()
            # Health input for fleet scoring: a probe that never answered
            # is at least as slow as the timeout we waited.
            self.last_ttft[ep.url] = self.timeout
            if registry is not None:
                registry.record_failure(ep.url)
            logger.debug("canary probe failed for %s: %s", ep.url, e)


# App-scoped (router.appscope): each router app runs its own prober.
_SCOPE_KEY = "canary_prober"


def initialize_canary_prober(
    interval: float, timeout: float = 5.0, api_key: Optional[str] = None
) -> CanaryProber:
    from .. import appscope

    return appscope.scoped_set(
        _SCOPE_KEY, CanaryProber(interval, timeout=timeout, api_key=api_key)
    )


def get_canary_prober() -> Optional[CanaryProber]:
    from .. import appscope

    return appscope.scoped_get(_SCOPE_KEY)


def teardown_canary_prober() -> None:
    from .. import appscope

    appscope.scoped_set(_SCOPE_KEY, None)
