"""OpenAI Files API: upload / list / retrieve / content / delete.

Capability parity with the reference's files surface
(``routers/files_router.py:23-81`` + ``services/files_service/``: Storage ABC
with a local-filesystem backend, chunked async writes via aiofiles, per-user
directories under the storage root).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import time
import uuid
from typing import Dict, List, Optional

import aiofiles
from aiohttp import web

from ...obs import error_headers

from ...logging_utils import init_logger

logger = init_logger(__name__)

_CHUNK = 1 << 20

# aiohttp percent-decodes match_info, so a file_id of ``..%2F..%2Fetc/passwd``
# reaches the storage layer as a relative path. Path components must match a
# strict allowlist — no separators, no '..' — before any filesystem use.
_SAFE_COMPONENT = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]*$")


def _check_component(name: str, what: str) -> str:
    if not _SAFE_COMPONENT.match(name) or ".." in name:
        raise ValueError(f"invalid {what}: {name!r}")
    if name.endswith(".json"):
        # A file id of '<fid>.json' would alias file <fid>'s metadata
        # sidecar, exposing or deleting another file's metadata.
        raise ValueError(f"invalid {what}: {name!r} (reserved suffix)")
    return name


@dataclasses.dataclass
class OpenAIFile:
    id: str
    bytes: int
    created_at: int
    filename: str
    purpose: str
    user: str = "anonymous"

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "object": "file",
            "bytes": self.bytes,
            "created_at": self.created_at,
            "filename": self.filename,
            "purpose": self.purpose,
        }


class FileStorage:
    """Local-FS storage: <base>/<user>/<file_id> + sidecar metadata json."""

    def __init__(self, base_path: str):
        self.base_path = base_path
        os.makedirs(base_path, exist_ok=True)

    def _dir(self, user: str) -> str:
        d = os.path.join(self.base_path, _check_component(user, "user"))
        os.makedirs(d, exist_ok=True)
        return d

    def _resolve(self, user: str, name: str) -> str:
        """Join + belt-and-braces realpath containment check."""
        path = os.path.join(self._dir(user), name)
        base = os.path.realpath(self.base_path)
        if os.path.commonpath([os.path.realpath(path), base]) != base:
            raise ValueError(f"path escapes storage root: {name!r}")
        return path

    def _meta_path(self, user: str, file_id: str) -> str:
        return self._resolve(user, _check_component(file_id, "file id") + ".json")

    def _data_path(self, user: str, file_id: str) -> str:
        return self._resolve(user, _check_component(file_id, "file id"))

    async def save_file(
        self,
        filename: str,
        purpose: str,
        content: Optional[bytes] = None,
        reader=None,
        user: str = "anonymous",
        file_id: Optional[str] = None,
    ) -> OpenAIFile:
        fid = file_id or f"file-{uuid.uuid4().hex}"
        path = self._data_path(user, fid)
        size = 0
        async with aiofiles.open(path, "wb") as f:
            if content is not None:
                await f.write(content)
                size = len(content)
            else:
                while True:
                    chunk = await reader.read_chunk(_CHUNK)
                    if not chunk:
                        break
                    await f.write(chunk)
                    size += len(chunk)
        info = OpenAIFile(
            id=fid, bytes=size, created_at=int(time.time()),
            filename=filename, purpose=purpose, user=user,
        )
        await self.write_meta(info)
        return info

    async def write_meta(self, info: OpenAIFile) -> None:
        async with aiofiles.open(self._meta_path(info.user, info.id), "w") as f:
            await f.write(json.dumps(dataclasses.asdict(info)))

    async def get_file(self, file_id: str, user: str = "anonymous") -> Optional[OpenAIFile]:
        meta = self._meta_path(user, file_id)
        if not os.path.exists(meta):
            return None
        async with aiofiles.open(meta) as f:
            return OpenAIFile(**json.loads(await f.read()))

    async def get_file_content(
        self, file_id: str, user: str = "anonymous"
    ) -> Optional[bytes]:
        path = self._data_path(user, file_id)
        if not os.path.exists(path):
            return None
        async with aiofiles.open(path, "rb") as f:
            return await f.read()

    async def list_files(self, user: str = "anonymous") -> List[OpenAIFile]:
        out = []
        d = self._dir(user)
        for name in sorted(os.listdir(d)):
            if name.endswith(".json"):
                async with aiofiles.open(os.path.join(d, name)) as f:
                    out.append(OpenAIFile(**json.loads(await f.read())))
        return out

    async def delete_file(self, file_id: str, user: str = "anonymous") -> bool:
        found = False
        for path in (self._data_path(user, file_id), self._meta_path(user, file_id)):
            if os.path.exists(path):
                os.remove(path)
                found = True
        return found


def install_files_api(app: web.Application, args) -> None:
    storage = FileStorage(args.file_storage_path)
    app["file_storage"] = storage

    async def upload(request: web.Request) -> web.Response:
        reader = await request.multipart()
        purpose, file_field = "batch", None
        filename = "upload"
        info = None
        async for field in reader:
            if field.name == "purpose":
                purpose = (await field.read()).decode()
            elif field.name == "file":
                filename = field.filename or "upload"
                info = await storage.save_file(filename, purpose, reader=field)
        if info is None:
            return web.json_response(
                {"error": {"message": "missing file field", "code": 400}},
                status=400, headers=error_headers(request),
            )
        if info.purpose != purpose:
            # Multipart field order is arbitrary: the purpose may arrive
            # after the file. Update the persisted sidecar too.
            info.purpose = purpose
            await storage.write_meta(info)
        return web.json_response(info.to_dict())

    async def list_(request: web.Request) -> web.Response:
        files = await storage.list_files()
        return web.json_response(
            {"object": "list", "data": [f.to_dict() for f in files]}
        )

    def _bad_id(e: ValueError, request: web.Request) -> web.Response:
        return web.json_response(
            {"error": {"message": str(e), "code": 400}},
            status=400, headers=error_headers(request),
        )

    async def get(request: web.Request) -> web.Response:
        try:
            info = await storage.get_file(request.match_info["file_id"])
        except ValueError as e:
            return _bad_id(e, request)
        if info is None:
            return web.json_response(
                {"error": {"message": "file not found", "code": 404}},
                status=404, headers=error_headers(request),
            )
        return web.json_response(info.to_dict())

    async def content(request: web.Request) -> web.Response:
        try:
            data = await storage.get_file_content(request.match_info["file_id"])
        except ValueError as e:
            return _bad_id(e, request)
        if data is None:
            return web.json_response(
                {"error": {"message": "file not found", "code": 404}},
                status=404, headers=error_headers(request),
            )
        return web.Response(body=data, content_type="application/octet-stream")

    async def delete(request: web.Request) -> web.Response:
        try:
            ok = await storage.delete_file(request.match_info["file_id"])
        except ValueError as e:
            return _bad_id(e, request)
        return web.json_response(
            {"id": request.match_info["file_id"], "object": "file", "deleted": ok}
        )

    app.router.add_post("/v1/files", upload)
    app.router.add_get("/v1/files", list_)
    app.router.add_get("/v1/files/{file_id}", get)
    app.router.add_get("/v1/files/{file_id}/content", content)
    app.router.add_delete("/v1/files/{file_id}", delete)
    logger.info("files API enabled at %s", args.file_storage_path)
