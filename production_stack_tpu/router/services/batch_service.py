"""OpenAI Batches API: SQLite-backed queue + background processor.

Capability parity with the reference's batch surface
(``routers/batches_router.py:23-113`` + ``services/batch_service/``:
``BatchProcessor`` ABC, SQLite-queued ``LocalBatchProcessor`` poll loop,
``BatchInfo/BatchStatus``). Two deliberate differences:

- the reference's processor *simulates* completions
  (``local_processor.py`` stub); this one actually executes each JSONL line
  against a discovered backend and writes real output/error files;
- aiosqlite is unavailable, so the stdlib ``sqlite3`` runs on the default
  executor (the queue is low-QPS control-plane state).
"""

from __future__ import annotations

import asyncio
import json
import os
import sqlite3
import time
import uuid
from enum import Enum
from typing import Any, Dict, List, Optional

import aiohttp
from aiohttp import web

from ...logging_utils import init_logger
from ...obs import error_headers
from ..hop import hop_headers
from ..service_discovery import get_service_discovery

logger = init_logger(__name__)


class BatchStatus(str, Enum):
    VALIDATING = "validating"
    IN_PROGRESS = "in_progress"
    COMPLETED = "completed"
    FAILED = "failed"
    CANCELLED = "cancelled"


_SCHEMA = """
CREATE TABLE IF NOT EXISTS batches (
    id TEXT PRIMARY KEY,
    input_file_id TEXT NOT NULL,
    endpoint TEXT NOT NULL,
    completion_window TEXT,
    status TEXT NOT NULL,
    created_at INTEGER NOT NULL,
    output_file_id TEXT,
    error_file_id TEXT,
    request_counts TEXT,
    metadata TEXT
)
"""


class LocalBatchProcessor:
    """Poll the queue, execute each batch's JSONL lines against backends."""

    def __init__(self, db_path: str, app: web.Application, poll_interval: float = 2.0):
        self.db_path = db_path
        self.app = app
        self.poll_interval = poll_interval
        self._task: Optional[asyncio.Task] = None

    # -- sqlite (executor-wrapped) ---------------------------------------

    def _db(self) -> sqlite3.Connection:
        conn = sqlite3.connect(self.db_path)
        conn.row_factory = sqlite3.Row
        return conn

    async def _execute(self, query: str, params=()) -> List[sqlite3.Row]:
        def run():
            with self._db() as conn:
                conn.execute(_SCHEMA)
                cur = conn.execute(query, params)
                rows = cur.fetchall()
                conn.commit()
                return rows

        return await asyncio.get_event_loop().run_in_executor(None, run)

    # -- public API -------------------------------------------------------

    async def create_batch(
        self, input_file_id: str, endpoint: str, completion_window: str,
        metadata: Optional[dict],
    ) -> Dict[str, Any]:
        batch_id = f"batch_{uuid.uuid4().hex}"
        await self._execute(
            "INSERT INTO batches (id, input_file_id, endpoint, completion_window,"
            " status, created_at, request_counts, metadata)"
            " VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
            (batch_id, input_file_id, endpoint, completion_window,
             BatchStatus.VALIDATING.value, int(time.time()),
             json.dumps({"total": 0, "completed": 0, "failed": 0}),
             json.dumps(metadata or {})),
        )
        return (await self.get_batch(batch_id))  # type: ignore[return-value]

    async def get_batch(self, batch_id: str) -> Optional[Dict[str, Any]]:
        rows = await self._execute("SELECT * FROM batches WHERE id = ?", (batch_id,))
        return self._row_to_dict(rows[0]) if rows else None

    async def list_batches(self, limit: int = 20) -> List[Dict[str, Any]]:
        rows = await self._execute(
            "SELECT * FROM batches ORDER BY created_at DESC LIMIT ?", (limit,)
        )
        return [self._row_to_dict(r) for r in rows]

    async def cancel_batch(self, batch_id: str) -> Optional[Dict[str, Any]]:
        await self._execute(
            "UPDATE batches SET status = ? WHERE id = ? AND status IN (?, ?)",
            (BatchStatus.CANCELLED.value, batch_id,
             BatchStatus.VALIDATING.value, BatchStatus.IN_PROGRESS.value),
        )
        return await self.get_batch(batch_id)

    @staticmethod
    def _row_to_dict(row: sqlite3.Row) -> Dict[str, Any]:
        return {
            "id": row["id"],
            "object": "batch",
            "endpoint": row["endpoint"],
            "input_file_id": row["input_file_id"],
            "completion_window": row["completion_window"],
            "status": row["status"],
            "created_at": row["created_at"],
            "output_file_id": row["output_file_id"],
            "error_file_id": row["error_file_id"],
            "request_counts": json.loads(row["request_counts"] or "{}"),
            "metadata": json.loads(row["metadata"] or "{}"),
        }

    # -- processing loop --------------------------------------------------

    async def start(self) -> None:
        # pstlint: task-owner=_task
        self._task = asyncio.create_task(self._loop())

    async def close(self) -> None:
        if self._task:
            self._task.cancel()

    async def _loop(self) -> None:
        while True:
            try:
                rows = await self._execute(
                    "SELECT id FROM batches WHERE status = ? ORDER BY created_at LIMIT 1",
                    (BatchStatus.VALIDATING.value,),
                )
                if rows:
                    await self._process(rows[0]["id"])
                    continue
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001
                logger.error("batch poll loop error: %s", e)
            await asyncio.sleep(self.poll_interval)

    async def _process(self, batch_id: str) -> None:
        batch = await self.get_batch(batch_id)
        storage = self.app.get("file_storage")
        if batch is None or storage is None:
            return
        content = await storage.get_file_content(batch["input_file_id"])
        if content is None:
            await self._execute(
                "UPDATE batches SET status = ? WHERE id = ?",
                (BatchStatus.FAILED.value, batch_id),
            )
            return
        lines = [ln for ln in content.decode().splitlines() if ln.strip()]
        await self._execute(
            "UPDATE batches SET status = ?, request_counts = ? WHERE id = ?",
            (BatchStatus.IN_PROGRESS.value,
             json.dumps({"total": len(lines), "completed": 0, "failed": 0}),
             batch_id),
        )

        outputs, errors = [], []
        completed = failed = 0
        session: aiohttp.ClientSession = self.app["client_session"]
        for line in lines:
            # Respect cancellation between requests.
            current = await self.get_batch(batch_id)
            if current and current["status"] == BatchStatus.CANCELLED.value:
                return
            try:
                item = json.loads(line)
                url = item.get("url") or batch["endpoint"]
                backend = self._pick_backend(item.get("body", {}).get("model"))
                if backend is None:
                    raise RuntimeError("no backend available for model")
                # Batch lines execute detached from any live client
                # request: each line gets its own id so engine logs and
                # /debug/requests timelines are joinable per line. They
                # ride the BATCH tier (docs/multi-tenancy.md) under the
                # creating tenant's identity: the engine scheduler admits
                # them weighted-fair behind interactive work and preempts
                # them first under page pressure — the /v1/batches
                # executor IS the lowest QoS tier.
                line_id = f"batch_req_{uuid.uuid4().hex[:12]}"
                line_headers = hop_headers(request_id=line_id)
                line_headers["X-PST-Tenant"] = (
                    batch.get("metadata", {}).get("pst_tenant") or "default"
                )
                line_headers["X-PST-Tenant-Class"] = "batch"
                async with session.post(
                    backend + url, json=item.get("body", {}),
                    headers=line_headers,
                ) as resp:
                    payload = await resp.json()
                    record = {
                        "id": line_id,
                        "custom_id": item.get("custom_id"),
                        "response": {"status_code": resp.status, "body": payload},
                        "error": None,
                    }
                    if resp.status == 200:
                        completed += 1
                        outputs.append(record)
                    else:
                        failed += 1
                        errors.append(record)
            except Exception as e:  # noqa: BLE001
                failed += 1
                errors.append({
                    "custom_id": (json.loads(line).get("custom_id")
                                  if line.startswith("{") else None),
                    "response": None,
                    "error": {"message": str(e)},
                })

        out_info = await storage.save_file(
            f"{batch_id}_output.jsonl", "batch_output",
            content="\n".join(json.dumps(o) for o in outputs).encode(),
        )
        err_id = None
        if errors:
            err_info = await storage.save_file(
                f"{batch_id}_errors.jsonl", "batch_output",
                content="\n".join(json.dumps(o) for o in errors).encode(),
            )
            err_id = err_info.id
        await self._execute(
            "UPDATE batches SET status = ?, output_file_id = ?, error_file_id = ?,"
            " request_counts = ? WHERE id = ?",
            (BatchStatus.COMPLETED.value if failed < len(lines) or not lines
             else BatchStatus.FAILED.value,
             out_info.id, err_id,
             json.dumps({"total": len(lines), "completed": completed,
                         "failed": failed}),
             batch_id),
        )
        logger.info("batch %s done: %d ok, %d failed", batch_id, completed, failed)

    def _pick_backend(self, model: Optional[str]) -> Optional[str]:
        eps = get_service_discovery().get_endpoint_info()
        candidates = [
            e.url for e in eps
            if not e.sleep and (model is None or model in e.model_names)
        ]
        return candidates[0] if candidates else None


def install_batch_api(app: web.Application, args) -> None:
    # Default the queue DB under this instance's file-storage root: a shared
    # host-global path would let two routers on one host steal each other's
    # queued batches (each marking the other's inputs missing → failed).
    db_path = getattr(args, "batch_db_path", None)
    if not db_path:
        root = getattr(args, "file_storage_path", None) or "/tmp/pst_files"
        os.makedirs(root, exist_ok=True)
        db_path = os.path.join(root, "batches.sqlite")
    processor = LocalBatchProcessor(db_path, app)
    app["batch_processor"] = processor

    async def create(request: web.Request) -> web.Response:
        body = await request.json()
        for field in ("input_file_id", "endpoint"):
            if field not in body:
                return web.json_response(
                    {"error": {"message": f"missing {field}", "code": 400}},
                    status=400, headers=error_headers(request),
                )
        metadata = dict(body.get("metadata") or {})
        # Record the creating tenant so the executor's lines bill to (and
        # are scheduled as) that tenant at the batch tier. /v1/batches is
        # not an admission path, so the identity is resolved here with
        # the same precedence (API key > header > default).
        tenant = request.get("tenant")
        if tenant is None:
            from ...resilience import get_tenant_config

            tenant_cfg = get_tenant_config()
            if tenant_cfg is not None:
                auth = request.headers.get("Authorization", "")
                key = auth[7:] if auth.startswith("Bearer ") else None
                tenant = tenant_cfg.resolve(request.headers, key)
        if tenant is not None:
            metadata.setdefault("pst_tenant", tenant.name)
        batch = await processor.create_batch(
            body["input_file_id"], body["endpoint"],
            body.get("completion_window", "24h"), metadata,
        )
        return web.json_response(batch)

    async def list_(request: web.Request) -> web.Response:
        limit = int(request.query.get("limit", "20"))
        return web.json_response(
            {"object": "list", "data": await processor.list_batches(limit)}
        )

    async def get(request: web.Request) -> web.Response:
        batch = await processor.get_batch(request.match_info["batch_id"])
        if batch is None:
            return web.json_response(
                {"error": {"message": "batch not found", "code": 404}},
                status=404, headers=error_headers(request),
            )
        return web.json_response(batch)

    async def cancel(request: web.Request) -> web.Response:
        batch = await processor.cancel_batch(request.match_info["batch_id"])
        if batch is None:
            return web.json_response(
                {"error": {"message": "batch not found", "code": 404}},
                status=404, headers=error_headers(request),
            )
        return web.json_response(batch)

    app.router.add_post("/v1/batches", create)
    app.router.add_get("/v1/batches", list_)
    app.router.add_get("/v1/batches/{batch_id}", get)
    app.router.add_post("/v1/batches/{batch_id}/cancel", cancel)
    logger.info("batch API enabled (db %s)", processor.db_path)
