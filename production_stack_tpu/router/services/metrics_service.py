"""Router-side Prometheus gauges, labeled per engine ``server``.

Capability parity with the reference's
``src/vllm_router/services/metrics_service/__init__.py:1-47``. Gauge names
keep the ``vllm:`` prefix so the reference Grafana dashboards
(observability/) work against this stack unchanged.
"""

from prometheus_client import Gauge

num_requests_running = Gauge(
    "vllm:num_requests_running", "Number of running requests", ["server"]
)
num_requests_waiting = Gauge(
    "vllm:num_requests_waiting", "Number of waiting requests", ["server"]
)
gpu_prefix_cache_hit_rate = Gauge(
    "vllm:gpu_prefix_cache_hit_rate", "KV prefix cache hit rate", ["server"]
)
gpu_prefix_cache_hits_total = Gauge(
    "vllm:gpu_prefix_cache_hits_total", "Total KV prefix cache hits", ["server"]
)
gpu_prefix_cache_queries_total = Gauge(
    "vllm:gpu_prefix_cache_queries_total", "Total KV prefix cache queries", ["server"]
)
gpu_cache_usage_perc = Gauge(
    "vllm:gpu_cache_usage_perc", "HBM KV cache usage fraction", ["server"]
)
current_qps = Gauge("vllm:current_qps", "Current queries per second", ["server"])
avg_decoding_length = Gauge(
    "vllm:avg_decoding_length", "Average decoding length (s)", ["server"]
)
num_prefill_requests = Gauge(
    "vllm:num_prefill_requests", "Requests in prefill", ["server"]
)
num_decoding_requests = Gauge(
    "vllm:num_decoding_requests", "Requests in decode", ["server"]
)
healthy_pods_total = Gauge(
    "vllm:healthy_pods_total", "Number of healthy engine pods", ["server"]
)
avg_latency = Gauge(
    "vllm:avg_latency", "Average end-to-end request latency (s)", ["server"]
)
avg_itl = Gauge("vllm:avg_itl", "Average inter-token latency (s)", ["server"])
num_requests_swapped = Gauge(
    "vllm:num_requests_swapped", "Number of swapped requests", ["server"]
)

# Router-process resource usage (Grafana "router CPU/mem/disk" panels).
router_cpu_percent = Gauge("pst_router:cpu_percent", "Router process CPU percent")
router_memory_mb = Gauge("pst_router:memory_mb", "Router process RSS (MB)")
router_disk_percent = Gauge("pst_router:disk_percent", "Router disk usage percent")
