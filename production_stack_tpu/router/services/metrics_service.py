"""Router-side Prometheus gauges, labeled per engine ``server``.

Capability parity with the reference's
``src/vllm_router/services/metrics_service/__init__.py:1-47``. Gauge names
keep the ``vllm:`` prefix so the reference Grafana dashboards
(observability/) work against this stack unchanged.

Also home of the fleet SLO surface (docs/observability.md "SLOs &
alerting"): ``pst_slo_*`` counters turn the BASELINE TTFT target into a
machine-checked ratio the generated ``observability/prometheus-rules.yaml``
burn-rate alerts page on, and ``pst_canary_*`` carries the canary
prober's per-engine synthetic TTFT.
"""

from typing import Optional

from prometheus_client import Counter, Gauge

num_requests_running = Gauge(
    "vllm:num_requests_running", "Number of running requests", ["server"]
)
num_requests_waiting = Gauge(
    "vllm:num_requests_waiting", "Number of waiting requests", ["server"]
)
gpu_prefix_cache_hit_rate = Gauge(
    "vllm:gpu_prefix_cache_hit_rate", "KV prefix cache hit rate", ["server"]
)
gpu_prefix_cache_hits_total = Gauge(
    "vllm:gpu_prefix_cache_hits_total", "Total KV prefix cache hits", ["server"]
)
gpu_prefix_cache_queries_total = Gauge(
    "vllm:gpu_prefix_cache_queries_total", "Total KV prefix cache queries", ["server"]
)
gpu_cache_usage_perc = Gauge(
    "vllm:gpu_cache_usage_perc", "HBM KV cache usage fraction", ["server"]
)
current_qps = Gauge("vllm:current_qps", "Current queries per second", ["server"])
avg_decoding_length = Gauge(
    "vllm:avg_decoding_length", "Average decoding length (s)", ["server"]
)
num_prefill_requests = Gauge(
    "vllm:num_prefill_requests", "Requests in prefill", ["server"]
)
num_decoding_requests = Gauge(
    "vllm:num_decoding_requests", "Requests in decode", ["server"]
)
healthy_pods_total = Gauge(
    "vllm:healthy_pods_total", "Number of healthy engine pods", ["server"]
)
avg_latency = Gauge(
    "vllm:avg_latency", "Average end-to-end request latency (s)", ["server"]
)
avg_itl = Gauge("vllm:avg_itl", "Average inter-token latency (s)", ["server"])
num_requests_swapped = Gauge(
    "vllm:num_requests_swapped", "Number of swapped requests", ["server"]
)

# Router-process resource usage (Grafana "router CPU/mem/disk" panels).
router_cpu_percent = Gauge("pst_router:cpu_percent", "Router process CPU percent")
router_memory_mb = Gauge("pst_router:memory_mb", "Router process RSS (MB)")
router_disk_percent = Gauge("pst_router:disk_percent", "Router disk usage percent")

# ---------------------------------------------------------------------------
# Fleet SLO surface (docs/observability.md "SLOs & alerting")
# ---------------------------------------------------------------------------

slo_requests_total = Counter(
    "pst_slo_requests",
    "Generation requests counted against the TTFT SLO (first upstream "
    "byte observed, or terminal upstream failure)",
    ["model"],
)
slo_ttft_within_target_total = Counter(
    "pst_slo_ttft_within_target",
    "Generation requests whose router-observed TTFT met the configured "
    "target (--slo-ttft-ms)",
    ["model"],
)
tenant_slo_requests_total = Counter(
    "pst_tenant_slo_requests",
    "Generation requests counted against the TTFT SLO, per tenant "
    "(tenant isolation on; same semantics as pst_slo_requests)",
    ["tenant"],
)
tenant_slo_ttft_within_target_total = Counter(
    "pst_tenant_slo_ttft_within_target",
    "Generation requests whose router-observed TTFT met the configured "
    "target, per tenant — the per-tenant SLO attainment numerator",
    ["tenant"],
)
canary_ttft_seconds = Gauge(
    "pst_canary_ttft_seconds",
    "Latest canary-probe TTFT per engine (synthetic 1-token completion)",
    ["engine"],
)
canary_failures_total = Counter(
    "pst_canary_failures",
    "Canary probes that failed outright (connect error or 5xx)",
    ["engine"],
)

# Configured at router bootstrap (--slo-ttft-ms; 0 disables the counters).
# App-scoped (router.appscope): two router apps in one process may run
# different TTFT objectives without overwriting each other.
_SLO_SCOPE_KEY = "slo_ttft_target_s"


def configure_slo(ttft_target_ms: float) -> None:
    from .. import appscope

    appscope.scoped_set(
        _SLO_SCOPE_KEY,
        ttft_target_ms / 1000.0 if ttft_target_ms and ttft_target_ms > 0
        else None,
    )


def slo_ttft_target_s() -> Optional[float]:
    from .. import appscope

    return appscope.scoped_get(_SLO_SCOPE_KEY)


def observe_slo_ttft(
    model: Optional[str], seconds: float, tenant: Optional[str] = None,
    trace_id: Optional[str] = None,
) -> None:
    """One request reached its first upstream byte: count it, and count it
    as within-target when the router-observed TTFT met the objective.
    With tenant isolation on, ``tenant`` feeds the per-tenant SLO view
    (``pst_tenant_slo_*``) beside the per-model one. ``trace_id``
    attaches as an OpenMetrics exemplar on the SLO counters, so a
    burn-rate alert links straight to a concrete request timeline."""
    target = slo_ttft_target_s()
    if target is None:
        return
    m = str(model) if model else "unknown"
    ex = {"trace_id": trace_id} if trace_id else None
    slo_requests_total.labels(model=m).inc(exemplar=ex)
    within = seconds <= target
    _feed_capacity(within)
    if within:
        slo_ttft_within_target_total.labels(model=m).inc(exemplar=ex)
    if tenant:
        tenant_slo_requests_total.labels(tenant=tenant).inc(exemplar=ex)
        if within:
            tenant_slo_ttft_within_target_total.labels(tenant=tenant).inc(
                exemplar=ex
            )


def observe_slo_failure(
    model: Optional[str], tenant: Optional[str] = None
) -> None:
    """A request failed before producing a first byte (exhausted failover,
    upstream 5xx): it consumed error budget without a TTFT sample."""
    if slo_ttft_target_s() is None:
        return
    slo_requests_total.labels(model=str(model) if model else "unknown").inc()
    _feed_capacity(False)
    if tenant:
        tenant_slo_requests_total.labels(tenant=tenant).inc()


def _feed_capacity(within: bool) -> None:
    """Mirror every SLO-counted event into the capacity monitor
    (docs/observability.md "Capacity signals"): the in-process burn rates
    /autoscale/signal serves are computed over EXACTLY the events the
    pst_slo_* counters export, so the two surfaces cannot diverge."""
    from .capacity import get_capacity_monitor

    monitor = get_capacity_monitor()
    if monitor is not None:
        monitor.observe(within)
