"""Disaggregated prefill/decode pools: pool split + the ``pst_disagg_*``
Prometheus surface (docs/disagg.md).

Pools are declarative fleet shape (``EndpointInfo.pool``: ``prefill`` |
``decode`` | ``fused``): helm's ``servingEngineSpec.pool`` / the static
``--static-pools`` list / the ``pst-pool`` pod label surface through
discovery, and the router's two-leg disagg flow routes each leg within its
pool. Fused engines stay eligible for BOTH legs, so a mixed fleet (or one
that lost a whole pool) degrades gracefully instead of 503ing.

Metrics declared in ``obs/metric_registry.py`` and documented in
docs/observability.md ("Disagg" rows); the ``metric-registry`` pstlint
check enforces the triangle.
"""

from __future__ import annotations

from typing import List

from prometheus_client import Counter, Histogram

POOL_PREFILL = "prefill"
POOL_DECODE = "decode"
POOL_FUSED = "fused"

transfer_seconds = Histogram(
    "pst_disagg_transfer_seconds",
    "Wall time of the disagg prefill leg (dispatch to completion signal) "
    "— the window the streamed KV transfer is overlapped into",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
)
overlap_seconds = Histogram(
    "pst_disagg_overlap_seconds",
    "Prefill wall overlapped with the decode leg's transfer+prefetch "
    "(decode leg dispatched this long before the prefill response "
    "returned; >0 = decode started before prefill finished)",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0, 10.0),
)
fallback_total = Counter(
    "pst_disagg_fallback",
    "Disagg requests that degraded to the fused path, by reason "
    "(prefill_error = prefill leg exhausted its retries, the decode "
    "engine recomputes; no_decode_backend = decode pool unroutable, "
    "served fused on the prefill pool; deadline = budget died in or "
    "between the legs). Decode-leg failover rides the ordinary "
    "pst_resilience_* counters — its last-resort candidate IS the "
    "prefill engine, i.e. the fused path.",
    ["reason"],
)


def endpoint_pool(endpoint) -> str:
    """An endpoint's declared pool, defaulting to fused (pre-pool
    endpoints and fleets that never declare pools behave exactly as
    before)."""
    pool = getattr(endpoint, "pool", None)
    return pool if pool in (POOL_PREFILL, POOL_DECODE) else POOL_FUSED


def pool_candidates(endpoints: List, pool: str) -> List:
    """The candidate list for one disagg leg: the pool's own engines plus
    fused ones. An empty pool returns every endpoint — mixed fleets (and
    fleets that lost a whole pool) degrade to the fused shape instead of
    failing the request."""
    own = [e for e in endpoints if endpoint_pool(e) == pool]
    fused = [e for e in endpoints if endpoint_pool(e) == POOL_FUSED]
    return (own + fused) if own or fused else list(endpoints)


def kv_health_penalty(endpoint, engine_stats) -> int:
    """A decode candidate's remote-KV degradation score: fused-recompute
    fallbacks plus corrupt replica copies its engine detected on read
    (scraped off engine /metrics — docs/kvserver.md). 0 when the engine
    has no stats yet, so undiscovered engines are never deprioritized."""
    stats = (engine_stats or {}).get(getattr(endpoint, "url", None))
    if stats is None:
        return 0
    return int(
        getattr(stats, "kv_transfer_fallbacks_total", 0)
        + getattr(stats, "kv_integrity_failures_total", 0)
    )


def order_by_kv_health(candidates: List, engine_stats) -> List:
    """Stable-sort a decode-leg candidate list so engines whose remote KV
    tier is degrading (fallbacks, integrity failures) sort behind healthy
    peers. Stable: within a penalty tier the pool ordering (own pool
    before fused) and the routing policy's own choice are preserved — this
    only *biases* the decode leg away from engines that keep recomputing
    transfers, it never excludes anyone (a fleet where every engine is
    degraded still routes)."""
    if not engine_stats:
        return list(candidates)
    return sorted(
        candidates, key=lambda e: kv_health_penalty(e, engine_stats)
    )


def fleet_has_pools(endpoints: List) -> bool:
    """Disagg is the fleet shape when both a prefill and a decode pool are
    declared — the router then runs the two-leg flow for every generation
    request regardless of routing policy (docs/disagg.md)."""
    pools = {endpoint_pool(e) for e in endpoints}
    return POOL_PREFILL in pools and POOL_DECODE in pools
