"""Pluggable request-body rewriting hook.

Capability parity with the reference's
``src/vllm_router/services/request_service/rewriter.py:30-119`` (ABC +
noop implementation + factory).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional

from ...logging_utils import init_logger

logger = init_logger(__name__)


class RequestRewriter(ABC):
    @abstractmethod
    def rewrite_request(self, request_body: str, model_name: str, endpoint: str) -> str:
        """Return the (possibly modified) request body."""


class NoopRequestRewriter(RequestRewriter):
    def rewrite_request(self, request_body: str, model_name: str, endpoint: str) -> str:
        return request_body


# App-scoped (router.appscope); absent scope entry degrades to noop.
_SCOPE_KEY = "request_rewriter"


def initialize_request_rewriter(rewriter_type: Optional[str] = None) -> RequestRewriter:
    from .. import appscope

    if rewriter_type in (None, "", "noop"):
        return appscope.scoped_set(_SCOPE_KEY, NoopRequestRewriter())
    raise ValueError(f"unknown request rewriter type {rewriter_type!r}")


def get_request_rewriter() -> RequestRewriter:
    from .. import appscope

    rewriter = appscope.scoped_get(_SCOPE_KEY)
    if rewriter is None:
        rewriter = appscope.scoped_set(_SCOPE_KEY, NoopRequestRewriter())
    return rewriter
