"""Pluggable request-body rewriting hook.

Capability parity with the reference's
``src/vllm_router/services/request_service/rewriter.py:30-119`` (ABC +
noop implementation + factory).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional

from ...logging_utils import init_logger

logger = init_logger(__name__)


class RequestRewriter(ABC):
    @abstractmethod
    def rewrite_request(self, request_body: str, model_name: str, endpoint: str) -> str:
        """Return the (possibly modified) request body."""


class NoopRequestRewriter(RequestRewriter):
    def rewrite_request(self, request_body: str, model_name: str, endpoint: str) -> str:
        return request_body


_rewriter: Optional[RequestRewriter] = None


def initialize_request_rewriter(rewriter_type: Optional[str] = None) -> RequestRewriter:
    global _rewriter
    if rewriter_type in (None, "", "noop"):
        _rewriter = NoopRequestRewriter()
    else:
        raise ValueError(f"unknown request rewriter type {rewriter_type!r}")
    return _rewriter


def get_request_rewriter() -> RequestRewriter:
    global _rewriter
    if _rewriter is None:
        _rewriter = NoopRequestRewriter()
    return _rewriter
