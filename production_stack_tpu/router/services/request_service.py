"""The proxy hot path: parse → route → stream, plus disagg P/D and sleep/wake.

Capability parity with the reference's
``src/vllm_router/services/request_service/request.py``
(route_general_request :139-301, process_request :54-136,
send_request_to_prefiller :304-322, send_request_to_decode :325-339,
route_disaggregated_prefill_request :342-434, route_sleep_wakeup_request
:437-513). aiohttp.web-native redesign: responses are
``web.StreamResponse`` generators; the shared upstream ClientSession
lives on the app.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Optional

import aiohttp
from aiohttp import web

from ...logging_utils import init_logger
from ..routing.logic import (
    DisaggregatedPrefillRouter,
    get_routing_logic,
)
from ..service_discovery import get_service_discovery
from ..stats.engine_stats import get_engine_stats_scraper
from ..stats.request_stats import get_request_stats_monitor
from .callbacks import get_custom_callback_handler
from .rewriter import get_request_rewriter

logger = init_logger(__name__)

# Hop-by-hop headers that must not be forwarded either direction.
_HOP_HEADERS = {
    "connection", "keep-alive", "proxy-authenticate", "proxy-authorization",
    "te", "trailers", "transfer-encoding", "upgrade", "host", "content-length",
}


def _forwardable(headers) -> dict:
    return {k: v for k, v in headers.items() if k.lower() not in _HOP_HEADERS}


def _error_response(status: int, message: str, etype: str = "invalid_request_error") -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": etype, "code": status}}, status=status
    )


async def proxy_and_stream(
    request: web.Request,
    backend_url: str,
    endpoint: str,
    body: bytes,
    request_id: str,
    debug_headers: Optional[dict] = None,
) -> web.StreamResponse:
    """Forward the request to ``backend_url``/``endpoint`` and stream back.

    The first upstream chunk marks TTFT (on_request_response); completion
    marks on_request_complete. Response content is accumulated only when a
    post-request hook (callbacks / semantic cache) needs it.
    """
    monitor = get_request_stats_monitor()
    callback = get_custom_callback_handler()
    session: aiohttp.ClientSession = request.app["client_session"]
    monitor.on_new_request(backend_url, request_id, time.time())

    collect = callback is not None and callback.post_request is not None
    semantic_store = request.app.get("semantic_cache_store")
    # Only buffer bodies the cache can actually use (non-streamed chat
    # completions) — otherwise long streams would pile up in router memory.
    parsed = request.get("parsed_json") or {}
    cacheable = (
        semantic_store is not None
        and endpoint == "/v1/chat/completions"
        and not parsed.get("stream")
    )
    collect = collect or cacheable
    collected = bytearray()

    try:
        async with session.request(
            request.method,
            backend_url + endpoint,
            data=body,
            headers=_forwardable(request.headers),
        ) as upstream:
            response = web.StreamResponse(status=upstream.status)
            for k, v in upstream.headers.items():
                if k.lower() not in _HOP_HEADERS:
                    response.headers[k] = v
            response.headers["X-Request-Id"] = request_id
            if debug_headers:
                for k, v in debug_headers.items():
                    response.headers[k] = v
            await response.prepare(request)
            async for chunk in upstream.content.iter_any():
                # First call records TTFT; subsequent calls record ITL.
                monitor.on_request_response(backend_url, request_id, time.time())
                if collect:
                    collected.extend(chunk)
                await response.write(chunk)
            monitor.on_request_complete(backend_url, request_id, time.time())
            await response.write_eof()
    except (aiohttp.ClientError, ConnectionResetError, OSError) as e:
        monitor.on_request_complete(backend_url, request_id, time.time())
        logger.error("backend %s failed for %s: %s", backend_url, request_id, e)
        return _error_response(502, f"backend error: {e}", "bad_gateway")

    if collect:
        content = bytes(collected)
        if semantic_store is not None:
            try:
                await semantic_store(request, content)
            except Exception as e:  # noqa: BLE001
                logger.debug("semantic cache store failed: %s", e)
        if callback is not None:
            try:
                await callback.call_post_request(request, content)
            except Exception as e:  # noqa: BLE001
                logger.error("post_request callback failed: %s", e)
    return response


async def route_general_request(request: web.Request, endpoint: str) -> web.StreamResponse:
    """Route an OpenAI-API request to an engine and stream the response."""
    request_id = request.headers.get("X-Request-Id") or str(uuid.uuid4())
    body = await request.read()
    try:
        request_json = json.loads(body) if body else {}
    except json.JSONDecodeError:
        return _error_response(400, "invalid JSON in request body")
    request["parsed_json"] = request_json  # for post-response hooks

    callback = get_custom_callback_handler()
    if callback is not None:
        short = await callback.call_pre_request(request, body, request_json)
        if short is not None:
            return short

    # PII gate (experimental, feature-gated).
    pii_check = request.app.get("pii_check")
    if pii_check is not None:
        blocked = await pii_check(request_json)
        if blocked is not None:
            return blocked

    discovery = get_service_discovery()
    endpoints = discovery.get_endpoint_info()

    requested_model = request_json.get("model", "")
    aliases = getattr(discovery, "aliases", None) or {}
    if requested_model in aliases:
        requested_model = aliases[requested_model]
        request_json["model"] = requested_model
        body = json.dumps(request_json).encode()

    # Rewriter hook (after alias resolution, before routing).
    rewriter = get_request_rewriter()
    rewritten = rewriter.rewrite_request(body.decode(), requested_model, endpoint)
    if rewritten != body.decode():
        body = rewritten.encode()
        request_json = json.loads(rewritten)
    # The store hook (proxy_and_stream) keys off parsed_json — keep it the
    # same dict the cache probe below sees, or check/store keys diverge.
    request["parsed_json"] = request_json

    # Semantic cache probe (experimental): a hit short-circuits routing
    # entirely (reference main_router.py:47-54 check_semantic_cache). Runs
    # after alias resolution + rewriting so cache lookups and stores key on
    # the same (resolved) model string and final message content.
    cache_check = request.app.get("semantic_cache_check")
    if cache_check is not None and endpoint == "/v1/chat/completions":
        cached = await cache_check(request_json)
        if cached is not None:
            return cached

    router = get_routing_logic()
    is_disagg = isinstance(router, DisaggregatedPrefillRouter)

    # Debug escape hatch: pin a specific engine by id with ?id=...
    pinned_id = request.query.get("id")
    if pinned_id:
        candidates = [e for e in endpoints if e.Id == pinned_id]
    elif is_disagg:
        # P/D pools serve under distinct labels; model filter happens per-pool.
        candidates = [e for e in endpoints if not e.sleep]
    else:
        candidates = [
            e for e in endpoints if (e.has_model(requested_model) and not e.sleep)
        ]
    if not candidates:
        return _error_response(
            404,
            f"model {requested_model!r} not found on any live engine",
            "not_found_error",
        )

    if is_disagg:
        return await route_disaggregated_prefill_request(
            request, endpoint, request_json, candidates, request_id
        )

    engine_stats = get_engine_stats_scraper().get_engine_stats()
    request_stats = get_request_stats_monitor().get_request_stats(time.time())
    try:
        backend_url = await router.route_request(
            candidates, engine_stats, request_stats, dict(request.headers), request_json
        )
    except ValueError as e:
        return _error_response(503, f"no backend available: {e}", "service_unavailable")
    logger.debug("routing %s for model %s to %s", request_id, requested_model, backend_url)
    return await proxy_and_stream(request, backend_url, endpoint, body, request_id)


async def route_disaggregated_prefill_request(
    request: web.Request,
    endpoint: str,
    request_json: dict,
    endpoints: list,
    request_id: str,
) -> web.StreamResponse:
    """Two-phase flow: prefill with max_tokens=1 (KV produced and shipped),
    then decode streams from the decode pool with the KV pulled in.
    """
    router = get_routing_logic()
    monitor = get_request_stats_monitor()
    engine_stats = get_engine_stats_scraper().get_engine_stats()
    request_stats = get_request_stats_monitor().get_request_stats(time.time())
    headers = dict(request.headers)

    original_max_tokens = request_json.get("max_tokens")
    original_stream = request_json.get("stream", False)
    prefill_json = dict(request_json)
    prefill_json["max_tokens"] = 1
    prefill_json["stream"] = False
    # Ask the engine to retain/publish KV for this request id so the decode
    # engine can fetch it (kv_transfer_params mirrors the reference's
    # connector config surface, deployment-vllm-multi.yaml:180-189).
    prefill_json.setdefault("kv_transfer_params", {})["request_id"] = request_id

    try:
        prefill_url = await router.route_request(
            endpoints, engine_stats, request_stats, headers, prefill_json
        )
    except ValueError as e:
        return _error_response(503, f"no prefill backend: {e}", "service_unavailable")

    session: aiohttp.ClientSession = request.app["client_session"]
    t_prefill_start = time.time()
    monitor.on_new_request(prefill_url, f"{request_id}-prefill", t_prefill_start)
    try:
        async with session.post(
            prefill_url + endpoint, json=prefill_json, headers=_forwardable(headers)
        ) as resp:
            resp.raise_for_status()
            await resp.json()
    except (aiohttp.ClientError, OSError) as e:
        monitor.on_request_complete(prefill_url, f"{request_id}-prefill", time.time())
        return _error_response(502, f"prefill failed: {e}", "bad_gateway")
    monitor.on_request_response(prefill_url, f"{request_id}-prefill", time.time())
    monitor.on_request_complete(prefill_url, f"{request_id}-prefill", time.time())
    logger.debug(
        "disagg prefill for %s done in %.3fs", request_id, time.time() - t_prefill_start
    )

    decode_json = dict(request_json)
    if original_max_tokens is not None:
        decode_json["max_tokens"] = original_max_tokens
    decode_json["stream"] = original_stream
    decode_json.setdefault("kv_transfer_params", {})["request_id"] = request_id
    decode_json["kv_transfer_params"]["prefill_url"] = prefill_url
    try:
        decode_url = await router.route_request(
            endpoints, engine_stats, request_stats, headers, decode_json
        )
    except ValueError as e:
        return _error_response(503, f"no decode backend: {e}", "service_unavailable")
    return await proxy_and_stream(
        request,
        decode_url,
        endpoint,
        json.dumps(decode_json).encode(),
        request_id,
        debug_headers={"X-Prefill-Url": prefill_url, "X-Decode-Url": decode_url},
    )


async def route_sleep_wakeup_request(request: web.Request, action: str) -> web.Response:
    """Admin proxy for /sleep, /wake_up, /is_sleeping across engines.

    Targets engines by ``model`` query-param label (or all engines when
    omitted), mirroring reference ``request.py:437-513``.
    """
    discovery = get_service_discovery()
    endpoints = discovery.get_endpoint_info()
    label = request.query.get("model")
    targets = [e for e in endpoints if label is None or e.model_label == label or label in e.model_names]
    if not targets:
        return _error_response(404, f"no engines matching {label!r}", "not_found_error")
    session: aiohttp.ClientSession = request.app["client_session"]
    results = {}
    for ep in targets:
        try:
            if action == "is_sleeping":
                async with session.get(f"{ep.url}/is_sleeping") as resp:
                    results[ep.url] = await resp.json()
            else:
                level = request.query.get("level")
                params = {"level": level} if level else None
                async with session.post(f"{ep.url}/{action}", params=params) as resp:
                    results[ep.url] = {"status": resp.status}
        except (aiohttp.ClientError, OSError) as e:
            results[ep.url] = {"error": str(e)}
    return web.json_response(results)
