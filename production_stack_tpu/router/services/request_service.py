"""The proxy hot path: parse → route → stream, plus disagg P/D and sleep/wake.

Capability parity with the reference's
``src/vllm_router/services/request_service/request.py``
(route_general_request :139-301, process_request :54-136,
send_request_to_prefiller :304-322, send_request_to_decode :325-339,
route_disaggregated_prefill_request :342-434, route_sleep_wakeup_request
:437-513). aiohttp.web-native redesign: responses are
``web.StreamResponse`` generators; the shared upstream ClientSession
lives on the app.

Resilience (no reference counterpart — the reference defers this to Envoy):

- every routing decision goes through ``route_with_resilience`` (circuit
  breakers + drain state consulted before the policy picks);
- ``proxy_and_stream`` retries with backoff and fails over to the
  next-best healthy engine on connect errors / 5xx, but NEVER *replays*
  after the first upstream byte has been streamed to the client;
- a committed SSE stream broken by engine death is *resumed* instead:
  the journal (``resilience/stream_resume.py``) re-issues the generation
  suffix on another engine and splices it seamlessly into the client
  stream (``--stream-resume``); when resume is off, ineligible, or
  exhausted, the truncation is made visible with an in-band error event
  + ``[DONE]`` rather than a silent cut;
- client disconnects mid-stream abort the upstream engine request instead
  of leaking a decoding sequence;
- per-request outcomes feed the breakers and ``pst_resilience_*`` metrics.

Deadlines & hedging (docs/resilience.md "Deadlines & hedging"):

- every attempt (main path, retries, disagg legs) forwards the request's
  *remaining* budget via ``X-PST-Deadline-Ms``; no attempt is made — and
  no retry is scheduled — that cannot fit the connect timeout inside the
  remaining budget (deadline sheds answer 504 + ``X-PST-Deadline-Exceeded``
  and never feed the breakers: an exhausted budget is not engine failure);
- non-streaming idempotent requests (completions/chat with
  ``stream=false``, embeddings, rerank, score) may be *hedged*: after a
  quantile-based delay a second attempt goes to the next-best healthy
  engine, the first usable response wins, and the loser is cancelled
  upstream. Hedges consult the breakers like any routing decision (a
  half-open breaker's probe slot IS the hedge), never fire at an open
  breaker, and are capped by ``HedgePolicy.max_outstanding_ratio``.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import time
import uuid
from typing import Awaitable, Callable, Optional

import aiohttp
from aiohttp import web

from ...logging_utils import init_logger
from ...obs import (
    NOOP_TRACE,
    REQUEST_ID_HEADER,
    TRACEPARENT_HEADER,
    error_headers,
)
from ...obs.logging import structured_logging_active
from ..hop import hop_headers
from ...resilience import (
    get_breaker_registry,
    get_default_deadline_ms,
    get_hedge_policy,
    get_retry_policy,
    get_stream_resume_policy,
)
from ...resilience import metrics as res_metrics
from ...resilience.breaker import BreakerState
from ...resilience.deadline import (
    DEADLINE_EXCEEDED_HEADER,
    Deadline,
    min_attempt_budget,
    parse_deadline,
    with_deadline_header,
)
from ...resilience.stream_resume import (
    StreamJournal,
    build_continuation,
    resume_eligible,
)
from ..routing.logic import (
    DisaggregatedPrefillRouter,
    get_routing_logic,
    route_with_resilience,
)
from ..service_discovery import get_service_discovery
from ..state import get_state_backend
from ..state import metrics as state_metrics
from ..stats.engine_stats import get_engine_stats_scraper
from ..stats.request_stats import get_request_stats_monitor
from ...obs.tasks import spawn_owned
from . import disagg
from .callbacks import get_custom_callback_handler
from .metrics_service import observe_slo_failure, observe_slo_ttft
from .rewriter import get_request_rewriter

logger = init_logger(__name__)

# Hop-by-hop headers that must not be forwarded either direction.
_HOP_HEADERS = {
    "connection", "keep-alive", "proxy-authenticate", "proxy-authorization",
    "te", "trailers", "transfer-encoding", "upgrade", "host", "content-length",
}

# The next backend to fail over to, given the set of already-tried URLs
# (None = nowhere left to go).
FailoverFn = Callable[[set], Awaitable[Optional[str]]]


def _forwardable(headers) -> dict:
    return {k: v for k, v in headers.items() if k.lower() not in _HOP_HEADERS}


def _tenant_headers(request: web.Request) -> dict:
    """The canonical tenant stamp for upstream hops: the ROUTER-resolved
    identity and tier (docs/multi-tenancy.md), overwriting whatever the
    client sent — the engine scheduler and fleet scoring must never trust
    a self-assigned class. Empty when tenancy is off (headers then pass
    through untouched, the pre-tenancy behavior)."""
    tenant = request.get("tenant")
    if tenant is None:
        return {}
    from ...resilience import TENANT_CLASS_HEADER, TENANT_HEADER

    return {TENANT_HEADER: tenant.name, TENANT_CLASS_HEADER: tenant.tier}


def _meter_tenant_usage(
    tenant, body: bytes, journal, collected: Optional[bytes], streaming: bool
) -> None:
    """Per-tenant token metering (billing): exact from the upstream's
    reported ``usage`` when available (journaled SSE accumulates it;
    non-streamed generations are buffered and parsed), falling back to a
    body-size estimate for prompt tokens and the journal's delivered
    chunk count for completion tokens. Metered once per request, on the
    path that reached a terminal state in this proxy call."""
    usage = None
    if journal is not None and isinstance(journal.usage, dict):
        usage = journal.usage
    elif not streaming and collected:
        try:
            parsed = json.loads(collected)
            if isinstance(parsed, dict) and isinstance(
                parsed.get("usage"), dict
            ):
                usage = parsed["usage"]
        except (ValueError, UnicodeDecodeError):
            usage = None
    tokens_in = 0.0
    tokens_out = 0.0
    if usage is not None:
        tokens_in = float(usage.get("prompt_tokens") or 0)
        tokens_out = float(usage.get("completion_tokens") or 0)
    if tokens_in <= 0:
        tokens_in = len(body) / 4.0  # chars-per-token estimate
    if tokens_out <= 0 and journal is not None:
        tokens_out = float(getattr(journal, "delivered_tokens", 0) or 0)
    if tokens_in > 0:
        res_metrics.tenant_usage_tokens_total.labels(
            tenant=tenant.label, direction="in"
        ).inc(tokens_in)
    if tokens_out > 0:
        res_metrics.tenant_usage_tokens_total.labels(
            tenant=tenant.label, direction="out"
        ).inc(tokens_out)


def _trace_headers(headers: dict, request_id: str, span) -> dict:
    """Outbound hop headers: ``X-Request-Id`` always (so engine logs and
    timelines join on one id even with tracing off), plus a W3C
    ``traceparent`` naming ``span`` as the parent when tracing is active.
    With tracing off the client's own traceparent (if any) passes through
    untouched — the router stays a transparent trace hop. Thin span-aware
    wrapper over the sanctioned :func:`..hop.hop_headers` builder."""
    return hop_headers(headers, request_id=request_id, span=span)


def _error_response(
    status: int, message: str, etype: str = "invalid_request_error",
    request_id: Optional[str] = None,
) -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": etype, "code": status}},
        status=status,
        headers=error_headers(request_id),
    )


def _deadline_response(
    message: str, stage: str, trace=None, request_id: Optional[str] = None
) -> web.Response:
    """504 for an exhausted budget, tagged so clients (and the tests) can
    tell a deadline shed apart from a generic upstream timeout. Counts the
    shed by stage (and as a span event on the trace); never feeds the
    breakers — an exhausted budget says nothing about engine health."""
    res_metrics.deadline_sheds_total.labels(stage=stage).inc()
    if trace is not None:
        trace.add_event("deadline_shed", stage=stage)
    return web.json_response(
        {"error": {"message": message, "type": "deadline_exceeded", "code": 504}},
        status=504,
        headers=error_headers(request_id, extra={DEADLINE_EXCEEDED_HEADER: "1"}),
    )


def _deadline_blocks_attempt(deadline: Optional[Deadline], extra: float = 0.0) -> bool:
    """Whether the remaining budget can no longer fit one upstream attempt
    (connect-timeout floor) plus ``extra`` (e.g. the retry backoff)."""
    if deadline is None:
        return False
    return deadline.remaining_s() < min_attempt_budget(get_retry_policy()) + extra


def _note_success(url: str) -> None:
    registry = get_breaker_registry()
    if registry is not None:
        registry.record_success(url)


def _note_failure(url: str, request_id: str = "", span=None) -> None:
    res_metrics.upstream_failures_total.labels(server=url).inc()
    get_request_stats_monitor().on_request_failed(url, request_id, time.time())
    registry = get_breaker_registry()
    if registry is not None:
        registry.record_failure(url)
        if span is not None:
            state = registry.state(url)
            if state is not BreakerState.CLOSED:
                # Breaker movement is part of the request's story: record
                # it on the span that observed the failure.
                span.add_event("breaker_state", server=url, state=state.value)


# Scale-to-zero wake-on-arrival (docs/autoscaling.md "Scale to zero"):
# how often a request held for a waking standby re-probes it, and the
# cap on how long it will hold before surfacing the 503 (a deadline
# header always bounds it tighter).
_WAKE_POLL_S = 0.25
_WAKE_WAIT_MAX_S = 30.0


async def _fire_wake(session: aiohttp.ClientSession, url: str) -> None:
    """POST /wake_up to a slept standby — the first admission arrival IS
    the wake signal for a scaled-to-zero pool. Best-effort: a failed wake
    surfaces as the sleeping 503s the caller already handles (and the
    operator's reconcile loop wakes the engine on its next pass)."""
    try:
        # pstlint: disable=hop-contract(admin wake of a slept standby, not a proxied client request — there is no deadline/trace context to forward; the woken engine serves many clients)
        async with session.post(
            url + "/wake_up", timeout=aiohttp.ClientTimeout(total=5)
        ) as resp:
            await resp.read()
            logger.info("woke sleeping engine %s (status %d)", url, resp.status)
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        logger.warning("wake_up POST to %s failed: %s", url, e)


# Content chunks between journal checkpoints on replicated routers: small
# enough that a takeover rarely loses more than a few tokens of splice
# budget, large enough that checkpointing stays off the per-chunk path.
_CHECKPOINT_EVERY = 8


def _shared_state_backend():
    """The state backend, only when it actually replicates (None for the
    in-memory default — journal checkpointing is pure overhead there:
    a single replica's death loses the process anyway)."""
    backend = get_state_backend()
    if backend is None or not backend.shared:
        return None
    return backend


def _maybe_checkpoint_journal(
    journal: Optional[StreamJournal], request_id: str
) -> None:
    """Checkpoint a resumable journal to the replicated backend every
    ``_CHECKPOINT_EVERY`` delivered content chunks, so a surviving replica
    can splice a continuation if this replica dies mid-stream."""
    if journal is None or not (journal.eligible and journal.record_text):
        return
    backend = _shared_state_backend()
    if backend is None:
        return
    if (
        journal.checkpointed_tokens is None
        or journal.delivered_tokens - journal.checkpointed_tokens
        >= _CHECKPOINT_EVERY
    ):
        journal.checkpointed_tokens = journal.delivered_tokens
        backend.checkpoint_journal(request_id, journal.to_snapshot())


def _drop_checkpoint(
    journal: Optional[StreamJournal], request_id: str
) -> None:
    """The stream reached a terminal state on THIS replica: retire its
    checkpoint fleet-wide so no survivor ever resumes a finished stream."""
    if journal is None or journal.checkpointed_tokens is None:
        return
    backend = _shared_state_backend()
    if backend is not None:
        backend.drop_journal(request_id)
        journal.checkpointed_tokens = None


def make_failover(candidates, headers: dict, request_json: Optional[dict]) -> FailoverFn:
    """Failover = re-route among the not-yet-tried candidates with fresh
    stats, through the same policy (and breaker filter) as the first pick."""

    async def failover(tried: set) -> Optional[str]:
        remaining = [e for e in candidates if e.url not in tried]
        if not remaining:
            return None
        engine_stats = get_engine_stats_scraper().get_engine_stats()
        request_stats = get_request_stats_monitor().get_request_stats(time.time())
        try:
            return await route_with_resilience(
                get_routing_logic(), remaining, engine_stats, request_stats,
                headers, request_json, exclude=tried,
            )
        except ValueError:
            return None

    return failover


async def _next_backend(
    failover: Optional[FailoverFn], tried: set, attempt: int
) -> Optional[str]:
    policy = get_retry_policy()
    if failover is None or policy is None or not policy.should_retry(attempt):
        return None
    return await failover(tried)


async def proxy_and_stream(
    request: web.Request,
    backend_url: str,
    endpoint: str,
    body: bytes,
    request_id: str,
    debug_headers: Optional[dict] = None,
    failover: Optional[FailoverFn] = None,
    deadline: Optional[Deadline] = None,
) -> web.StreamResponse:
    """Forward the request to ``backend_url``/``endpoint`` and stream back.

    The first upstream chunk marks TTFT (on_request_response); completion
    marks on_request_complete. Response content is accumulated only when a
    post-request hook (callbacks / semantic cache) needs it.

    Failure handling: a connect error or 5xx *before the first streamed
    byte* re-routes to the next-best healthy engine (with backoff). Once a
    byte has reached the client the stream is committed — it must never be
    *replayed*. A mid-stream upstream death on a journaled SSE stream is
    *resumed* instead (continuation on another engine, spliced into the
    client stream); when resume is off, ineligible, or exhausted the
    truncation is terminated visibly (in-band error event + ``[DONE]``).
    A mid-stream client disconnect aborts the upstream request.

    Deadline handling: every attempt forwards the *remaining* budget via
    ``X-PST-Deadline-Ms``; a retry is only attempted if the budget still
    fits backoff + connect timeout, and an exhausted budget answers 504
    (``X-PST-Deadline-Exceeded``) without feeding the breakers. For
    non-streaming requests the whole attempt is bounded by the remaining
    budget; streams are bounded at connect only — once committed they run
    to completion (the engine sheds expired sequences itself).
    """
    monitor = get_request_stats_monitor()
    callback = get_custom_callback_handler()
    policy = get_retry_policy()
    session: aiohttp.ClientSession = request.app["client_session"]
    trace = request.get("trace") or NOOP_TRACE

    collect = callback is not None and callback.post_request is not None
    semantic_store = request.app.get("semantic_cache_store")
    # Only buffer bodies the cache can actually use (non-streamed chat
    # completions) — otherwise long streams would pile up in router memory.
    parsed = request.get("parsed_json") or {}
    cacheable = (
        semantic_store is not None
        and endpoint == "/v1/chat/completions"
        and not parsed.get("stream")
    )
    # Tenant metering (docs/multi-tenancy.md): non-streamed generations
    # are buffered so the upstream's exact usage can be billed; streams
    # meter from the journal's accumulated usage/chunk counts.
    tenant = request.get("tenant")
    meter_nonstream = (
        tenant is not None
        and not parsed.get("stream")
        and endpoint in ("/v1/completions", "/v1/chat/completions")
    )
    collect = collect or cacheable or meter_nonstream

    url = backend_url
    tried = {url}
    attempt = 0
    streaming = bool(parsed.get("stream"))
    # Scale-to-zero wake-on-arrival: engines we already fired /wake_up at
    # (once per request), and the monotonic cap on holding the request
    # for a wake when there is no other engine to fail over to.
    woken: set = set()
    wake_wait_until: Optional[float] = None

    # SLO accounting (docs/observability.md "SLOs & alerting"): the
    # router-observed TTFT — proxy entry to the first upstream byte of the
    # winning attempt, retries and backoff included, because that is what
    # the client experienced. Counted once per request.
    slo_eligible = endpoint in ("/v1/completions", "/v1/chat/completions")
    slo_model = parsed.get("model") if isinstance(parsed, dict) else None
    slo_t0 = time.monotonic()
    slo_done = False

    completed = False

    while True:
        if deadline is not None and deadline.expired():
            # The budget died between attempts (backoff, slow routing):
            # never forward work that is already expired.
            return _deadline_response(
                "deadline exceeded before upstream attempt", "router_proxy",
                trace=trace, request_id=request_id,
            )
        attempt_span = trace.span(
            "proxy_attempt",
            attributes={"server": url, "attempt": attempt, "endpoint": endpoint},
        )
        # Per-attempt timeouts: connect bounds the TCP handshake, sock_read
        # the gap between reads, so a black-holed backend raises a
        # retryable TimeoutError instead of hanging the client forever.
        # With a deadline, non-streaming attempts are additionally bounded
        # end-to-end by the remaining budget (recomputed per attempt);
        # streams stay unbounded on total — the engine sheds expired
        # sequences between decode steps itself.
        remaining = deadline.remaining_s() if deadline is not None else None
        connect_t = (policy.connect_timeout or None) if policy else None
        if connect_t is not None and remaining is not None:
            connect_t = min(connect_t, max(remaining, 0.001))
        attempt_timeout = aiohttp.ClientTimeout(
            total=(
                max(remaining, 0.001)
                if remaining is not None and not streaming
                else None
            ),
            connect=connect_t,
            sock_read=(policy.read_timeout or None) if policy else None,
        )
        fwd_headers = _trace_headers(
            with_deadline_header(_forwardable(request.headers), deadline),
            request_id, attempt_span,
        )
        # Canonical tenant stamp LAST: it must overwrite any client-sent
        # tenant headers that survived _forwardable.
        fwd_headers.update(_tenant_headers(request))
        collected = bytearray()
        response: Optional[web.StreamResponse] = None
        journal: Optional[StreamJournal] = None
        failure_noted = False  # at most one breaker/stats failure per attempt
        completed = False  # ... and at most one completion per attempt
        standby_503 = False  # sleeping/warming rejection from a woken standby

        def _complete() -> None:
            # Idempotent per attempt: write_eof raising after the stream
            # completed (or cancellation racing completion) must not record
            # a second completion — the monitor would steal a prefill slot
            # from a concurrent request and skew the routing stats.
            nonlocal completed
            if not completed:
                completed = True
                monitor.on_request_complete(url, request_id, time.time())

        monitor.on_new_request(url, request_id, time.time())
        try:
            async with session.request(
                request.method,
                url + endpoint,
                data=body,
                headers=fwd_headers,
                timeout=attempt_timeout,
            ) as upstream:
                ok = not (
                    policy.is_retryable_status(upstream.status)
                    if policy is not None
                    else upstream.status >= 500
                )
                if (
                    not ok
                    and upstream.status == 504
                    and DEADLINE_EXCEEDED_HEADER in upstream.headers
                ):
                    # The engine shed this request's exhausted budget — a
                    # deliberate deadline shed, not engine failure: no
                    # breaker feed, and no retry (the budget downstream of
                    # which the engine shed is gone for us too). Stream the
                    # tagged 504 through.
                    ok = True
                if not ok:
                    if upstream.status == 503 and "X-PST-Draining" in upstream.headers:
                        # Deliberate drain rejection, not a failure: leave
                        # the breaker and failure stats alone, and reconcile
                        # discovery right here — this is how an
                        # engine-initiated drain (e.g. the preStop hook
                        # POSTing the engine directly) becomes unroutable
                        # even when no health-probe loop is running.
                        get_service_discovery().set_draining(url, True)
                    elif upstream.status == 503 and "X-PST-Warming" in upstream.headers:
                        # Warming (startup precompile) rejection — same
                        # rule: mark the endpoint unroutable from live
                        # traffic (the /ready probes clear it once the
                        # pass finishes), spare the breaker, fail over.
                        get_service_discovery().set_warming(url, True)
                        # A wake this request fired re-enters the warmup
                        # pass — keep holding for it below if there is
                        # nowhere else to go.
                        standby_503 = url in woken
                    elif upstream.status == 503 and "X-PST-Sleeping" in upstream.headers:
                        # Slept standby (scale-to-zero): the first arrival
                        # IS the wake signal. Fire the wake once, mark the
                        # endpoint warming (wake re-enters the warmup pass;
                        # the /ready probes clear it), spare the breaker,
                        # and fail over — or hold for the wake below when
                        # this was the only routable engine.
                        get_service_discovery().set_warming(url, True)
                        if url not in woken:
                            woken.add(url)
                            await _fire_wake(session, url)
                            # The standby is waking: clear the sleep mark the
                            # operator's fan-out set (static discovery has no
                            # probe loop to reconcile it); warming gates
                            # routability until the wake pass finishes.
                            get_service_discovery().set_sleeping(url, False)
                        standby_503 = True
                    else:
                        _note_failure(url, request_id, span=attempt_span)
                        failure_noted = True
                    backoff = policy.backoff(attempt) if policy else 0.0
                    if _deadline_blocks_attempt(deadline, backoff):
                        # A retry that cannot fit backoff + connect inside
                        # the remaining budget is doomed work: shed instead
                        # of forwarding (the 5xx still passes through below
                        # when the budget is merely tight, 504 when gone).
                        res_metrics.deadline_sheds_total.labels(
                            stage="router_retry"
                        ).inc()
                        next_url = None
                    else:
                        next_url = await _next_backend(failover, tried, attempt)
                    if next_url is not None:
                        _complete()
                        logger.warning(
                            "backend %s returned %d for %s; failing over to %s",
                            url, upstream.status, request_id, next_url,
                        )
                        attempt_span.set_attribute(
                            "http.status_code", upstream.status
                        )
                        attempt_span.set_attribute("outcome", "failover")
                        attempt_span.end()
                        res_metrics.retries_total.labels(server=url).inc()
                        res_metrics.failovers_total.inc()
                        # Give the connection back before sleeping: a
                        # backoff with the error body unread would park a
                        # connector slot per in-flight failover, exactly
                        # when the pool is under failure-induced load.
                        upstream.release()
                        await asyncio.sleep(policy.backoff(attempt))
                        attempt += 1
                        url = next_url
                        tried.add(url)
                        continue
                    if not ok and standby_503:
                        # Scale-to-zero with a single standby: nowhere to
                        # fail over, but a wake is in flight — hold the
                        # request (bounded by the wake cap and any
                        # deadline) and retry the same engine instead of
                        # surfacing the 503 to the client.
                        now_m = time.monotonic()
                        if wake_wait_until is None:
                            wake_wait_until = now_m + _WAKE_WAIT_MAX_S
                        if now_m < wake_wait_until and not _deadline_blocks_attempt(
                            deadline, _WAKE_POLL_S
                        ):
                            _complete()
                            attempt_span.set_attribute("outcome", "wake_wait")
                            attempt_span.end()
                            upstream.release()
                            await asyncio.sleep(_WAKE_POLL_S)
                            continue
                    # Nowhere left to go: stream the 5xx through unchanged.
                if ok and url in woken:
                    # The woken standby answered live traffic: clear the
                    # warming mark the wake path set (K8s discovery has no
                    # probe loop to reconcile it between pod events).
                    get_service_discovery().set_warming(url, False)
                try:
                    response = web.StreamResponse(status=upstream.status)
                    for k, v in upstream.headers.items():
                        if k.lower() not in _HOP_HEADERS:
                            response.headers[k] = v
                    response.headers["X-Request-Id"] = request_id
                    if debug_headers:
                        for k, v in debug_headers.items():
                            response.headers[k] = v
                    await response.prepare(request)
                    if (
                        streaming
                        and ok
                        and upstream.status == 200
                        and endpoint in ("/v1/completions", "/v1/chat/completions")
                        and "text/event-stream"
                        in (upstream.headers.get("Content-Type") or "")
                    ):
                        # Journaled stream: forward only complete SSE
                        # events (a partial frame in flight when the
                        # engine dies must not corrupt client framing)
                        # while accumulating the resume state. Text is
                        # only recorded when a resume could actually use
                        # it — never buffer N long streams for nothing.
                        resume_policy = get_stream_resume_policy()
                        eligible = resume_eligible(endpoint, parsed)
                        journal = StreamJournal(
                            endpoint.endswith("/chat/completions"),
                            request_json=parsed,
                            eligible=eligible,
                            record_text=(
                                eligible
                                and resume_policy is not None
                                and resume_policy.enabled
                            ),
                        )
                    first_byte = True
                    async for chunk in upstream.content.iter_any():
                        # First call records TTFT; subsequent calls record ITL.
                        monitor.on_request_response(url, request_id, time.time())
                        if first_byte:
                            attempt_span.add_event("first_byte")
                            first_byte = False
                            if slo_eligible and not slo_done:
                                # A first byte of an error body is not a
                                # first token: it burns error budget.
                                slo_done = True
                                if ok and upstream.status < 400:
                                    observe_slo_ttft(
                                        slo_model,
                                        time.monotonic() - slo_t0,
                                        tenant=(
                                            tenant.label
                                            if tenant is not None else None
                                        ),
                                        trace_id=(
                                            getattr(
                                                attempt_span, "trace_id", ""
                                            ) or None
                                        ),
                                    )
                                else:
                                    observe_slo_failure(
                                        slo_model,
                                        tenant=(
                                            tenant.label
                                            if tenant is not None else None
                                        ),
                                    )
                        if journal is not None:
                            chunk = journal.feed(chunk)
                            _maybe_checkpoint_journal(journal, request_id)
                            if not chunk:
                                continue
                        if collect:
                            collected.extend(chunk)
                        await response.write(chunk)
                    if journal is not None:
                        # Clean stream end: forward any buffered tail
                        # verbatim (well-formed SSE leaves none).
                        tail = journal.flush_raw()
                        if tail:
                            if collect:
                                collected.extend(tail)
                            await response.write(tail)
                    _complete()
                    if ok:
                        _note_success(url)
                    attempt_span.set_attribute("http.status_code", upstream.status)
                    attempt_span.set_attribute(
                        "outcome", "ok" if ok else "error_passthrough"
                    )
                    # End only after write_eof: a client disconnect raised
                    # there must still be able to flip the outcome before
                    # the span is sealed (end() is idempotent, so the
                    # disconnect/cancel handlers' end() wins the race).
                    await response.write_eof()
                    attempt_span.end()
                except (ConnectionResetError, ConnectionError):
                    # Client-side socket error on prepare/write/write_eof:
                    # the client went away — not a backend failure, so don't
                    # feed the breaker or replay the request. Abort the
                    # upstream request so the engine stops decoding for a
                    # dead consumer. (Upstream read errors surface as
                    # aiohttp.ClientError and still hit the outer handler.)
                    res_metrics.client_disconnects_total.inc()
                    _complete()
                    upstream.close()
                    # The consumer is gone for good: no survivor should
                    # ever resume this stream. (Deliberately NOT dropped
                    # on CancelledError below — a rolling-restart SIGTERM
                    # cancels handlers, and that checkpoint is exactly
                    # what the surviving replica resumes from.)
                    _drop_checkpoint(journal, request_id)
                    attempt_span.set_attribute("outcome", "client_disconnect")
                    attempt_span.end()
                    logger.info(
                        "client disconnected during response for %s; "
                        "aborted upstream %s", request_id, url,
                    )
                    return response
                except asyncio.CancelledError:
                    # aiohttp cancels the handler when the client drops the
                    # connection (also raised on server shutdown): same
                    # obligation either way — don't leak the upstream
                    # request — but only a dead client transport is a
                    # client disconnect; a router restart with N in-flight
                    # streams must not add N to the disconnect counter.
                    if request.transport is None or request.transport.is_closing():
                        res_metrics.client_disconnects_total.inc()
                    _complete()
                    upstream.close()
                    attempt_span.set_attribute("outcome", "cancelled")
                    attempt_span.end()
                    raise
        except (
            aiohttp.ClientError, asyncio.TimeoutError, ConnectionResetError, OSError,
        ) as e:
            _complete()
            attempt_span.set_attribute("error", str(e))
            if response is not None and response.prepared:
                if not failure_noted:
                    _note_failure(url, request_id, span=attempt_span)
                # Bytes already reached the client: the stream is committed
                # and must never be replayed (a replay would duplicate
                # already-delivered tokens).
                logger.error(
                    "backend %s died mid-stream for %s: %s", url, request_id, e
                )
                attempt_span.set_attribute("outcome", "midstream_death")
                attempt_span.end()
                if journal is not None:
                    # Journaled SSE stream: resume the generation on
                    # another engine (continuation of the suffix — not a
                    # replay) or terminate the truncation visibly.
                    outcome = await _resume_or_truncate(
                        request, response, journal, endpoint, request_id,
                        failover, tried, deadline, trace, collect, collected,
                    )
                    # Whatever the outcome, the stream reached a terminal
                    # state HERE — no survivor may resume it.
                    _drop_checkpoint(journal, request_id)
                    if outcome == "completed":
                        break  # run the post-response hooks below
                    return response
                with contextlib.suppress(Exception):
                    await response.write_eof()
                return response
            if deadline is not None and deadline.expired():
                # The budget ran out mid-attempt (the non-streaming total
                # timeout fires exactly at the deadline): this is a client
                # budget shed, not an engine failure — 504 and leave the
                # breaker alone.
                logger.info(
                    "deadline exceeded during attempt to %s for %s",
                    url, request_id,
                )
                attempt_span.set_attribute("outcome", "deadline_shed")
                attempt_span.end()
                return _deadline_response(
                    "deadline exceeded during upstream attempt", "router_proxy",
                    trace=trace, request_id=request_id,
                )
            if not failure_noted:
                _note_failure(url, request_id, span=attempt_span)
            backoff = policy.backoff(attempt) if policy else 0.0
            if _deadline_blocks_attempt(deadline, backoff):
                res_metrics.deadline_sheds_total.labels(
                    stage="router_retry"
                ).inc()
                next_url = None
            else:
                next_url = await _next_backend(failover, tried, attempt)
            if next_url is None:
                logger.error("backend %s failed for %s: %s", url, request_id, e)
                attempt_span.set_attribute("outcome", "error")
                attempt_span.end()
                if slo_eligible and not slo_done:
                    # Exhausted failover with zero bytes delivered: the
                    # request burns error budget (no TTFT sample exists).
                    slo_done = True
                    observe_slo_failure(
                        slo_model,
                        tenant=tenant.label if tenant is not None else None,
                    )
                return _error_response(502, f"backend error: {e}", "bad_gateway",
                                       request_id=request_id)
            logger.warning(
                "backend %s unreachable for %s (%s); failing over to %s",
                url, request_id, e, next_url,
            )
            attempt_span.set_attribute("outcome", "failover")
            attempt_span.end()
            res_metrics.retries_total.labels(server=url).inc()
            res_metrics.failovers_total.inc()
            await asyncio.sleep(policy.backoff(attempt))
            attempt += 1
            url = next_url
            tried.add(url)
            continue
        break  # attempt finished cleanly: run the post-response hooks

    _drop_checkpoint(journal, request_id)
    if tenant is not None:
        _meter_tenant_usage(
            tenant, body, journal,
            bytes(collected) if collect else None, streaming,
        )
    if collect:
        content = bytes(collected)
        if cacheable:
            try:
                await semantic_store(request, content)
            except Exception as e:  # noqa: BLE001
                logger.debug("semantic cache store failed: %s", e)
        if callback is not None:
            try:
                await callback.call_post_request(request, content)
            except Exception as e:  # noqa: BLE001
                logger.error("post_request callback failed: %s", e)
    return response


async def _resume_or_truncate(
    request: web.Request,
    response: web.StreamResponse,
    journal: StreamJournal,
    endpoint: str,
    request_id: str,
    failover: Optional[FailoverFn],
    tried: set,
    deadline: Optional[Deadline],
    trace,
    collect: bool,
    collected: bytearray,
) -> str:
    """A journaled stream just lost its upstream mid-generation: resume it
    on another engine when allowed, otherwise terminate the truncation
    *visibly* (in-band error event + ``[DONE]`` — never a silent cut).
    Returns ``completed`` | ``truncated`` | ``client_gone``."""
    policy = get_stream_resume_policy()
    enabled = policy is not None and policy.enabled
    if journal.saw_done:
        # The terminal [DONE] already reached the client — the transport
        # died between it and EOF. The stream is complete, not truncated;
        # nothing was resumed either, so no counter moves.
        with contextlib.suppress(Exception):
            await response.write_eof()
        return "completed"
    outcome = None
    if enabled and journal.resumable():
        outcome = await _resume_stream(
            request, response, journal, endpoint, request_id,
            failover, tried, deadline, trace, collect, collected,
        )
    if outcome == "completed":
        res_metrics.stream_resume_success_total.inc()
        with contextlib.suppress(Exception):
            await response.write_eof()
        return "completed"
    if outcome == "client_gone":
        return "client_gone"
    if journal.saw_error:
        # Engine-reported in-band error (original leg or a continuation):
        # the client saw it — deliberate rejection, not a resume failure.
        reason = "engine_error"
    elif outcome == "failed":
        res_metrics.stream_resume_failures_total.inc()
        reason = "resume_failed"
    elif not enabled:
        reason = "disabled"
    else:
        reason = "ineligible"
    res_metrics.stream_truncated_total.labels(reason=reason).inc()
    trace.add_event("stream_truncated", reason=reason,
                    delivered_tokens=journal.delivered_tokens)
    logger.error(
        "stream %s truncated after %d tokens (%s)",
        request_id, journal.delivered_tokens, reason,
    )
    tail = journal.truncation_tail()
    with contextlib.suppress(Exception):
        if tail:
            if collect:
                collected.extend(tail)
            await response.write(tail)
        await response.write_eof()
    return "truncated"


async def _resume_stream(
    request: web.Request,
    response: web.StreamResponse,
    journal: StreamJournal,
    endpoint: str,
    request_id: str,
    failover: Optional[FailoverFn],
    tried: set,
    deadline: Optional[Deadline],
    trace,
    collect: bool,
    collected: bytearray,
) -> str:
    """Issue continuation legs until the stream completes or the budget
    (legs, deadline, candidates) runs out. Each leg goes to the next-best
    healthy engine via the same breaker-consulting routing as a failover —
    with the prefix-aware policy the continuation lands where the KV for
    the shared prefix is warm. Returns ``completed`` | ``failed`` |
    ``client_gone``."""
    policy = get_stream_resume_policy()
    retry = get_retry_policy()
    monitor = get_request_stats_monitor()
    session: aiohttp.ClientSession = request.app["client_session"]

    async def _write(data: bytes) -> None:
        if collect:
            collected.extend(data)
        await response.write(data)

    while True:
        if journal.saw_error:
            # An engine-reported in-band error frame is on the wire (this
            # leg or a previous one): a deliberate rejection — never keep
            # resuming past it.
            return "failed"
        if journal.saw_done:
            return "completed"
        remaining_tokens = journal.remaining_tokens()
        if journal.finish_reason is not None or (
            remaining_tokens is not None and remaining_tokens <= 0
        ):
            # Generation already complete — the engine died between the
            # last token and the terminal framing. Finish locally from the
            # journal; no continuation leg needed.
            try:
                await _write(journal.synthesize_tail())
            except (ConnectionResetError, ConnectionError):
                res_metrics.client_disconnects_total.inc()
                return "client_gone"
            return "completed"
        if journal.legs >= policy.max_legs:
            logger.warning(
                "stream %s: resume legs exhausted (%d)",
                request_id, journal.legs,
            )
            return "failed"
        if _deadline_blocks_attempt(deadline):
            # A continuation the budget cannot cover (connect + one token)
            # is doomed work — same gate as a retry.
            res_metrics.deadline_sheds_total.labels(stage="router_retry").inc()
            return "failed"
        next_url = await failover(tried) if failover is not None else None
        if next_url is None:
            return "failed"
        journal.legs += 1
        leg = journal.legs
        tried.add(next_url)
        res_metrics.stream_resume_attempts_total.inc()
        cont_body = json.dumps(
            build_continuation(journal.request_json, journal, endpoint)
        ).encode()
        span = trace.span(
            "stream_resume",
            attributes={"server": next_url, "leg": leg, "endpoint": endpoint,
                        "delivered_tokens": journal.delivered_tokens},
        )
        rid = f"{request_id}-resume{leg}"
        fwd = _trace_headers(
            with_deadline_header(_forwardable(request.headers), deadline),
            request_id, span,
        )
        fwd.update(_tenant_headers(request))
        remaining_s = deadline.remaining_s() if deadline is not None else None
        connect_t = (retry.connect_timeout or None) if retry else None
        if connect_t is not None and remaining_s is not None:
            connect_t = min(connect_t, max(remaining_s, 0.001))
        timeout = aiohttp.ClientTimeout(
            total=None,  # streams run as long as the generation does
            connect=connect_t,
            sock_read=(retry.read_timeout or None) if retry else None,
        )
        logger.warning(
            "resuming stream %s on %s (leg %d, %d tokens delivered)",
            request_id, next_url, leg, journal.delivered_tokens,
        )
        monitor.on_new_request(next_url, rid, time.time())
        try:
            async with session.post(
                next_url + endpoint, data=cont_body, headers=fwd,
                timeout=timeout,
            ) as upstream:
                if upstream.status != 200 or "text/event-stream" not in (
                    upstream.headers.get("Content-Type") or ""
                ):
                    monitor.on_request_complete(next_url, rid, time.time())
                    if (
                        upstream.status == 503
                        and "X-PST-Draining" in upstream.headers
                    ):
                        get_service_discovery().set_draining(next_url, True)
                        span.set_attribute("outcome", "draining")
                    elif (
                        upstream.status == 503
                        and "X-PST-Warming" in upstream.headers
                    ):
                        get_service_discovery().set_warming(next_url, True)
                        span.set_attribute("outcome", "warming")
                    else:
                        _note_failure(next_url, rid, span=span)
                        span.set_attribute("outcome", "error")
                    span.set_attribute("http.status_code", upstream.status)
                    span.end()
                    continue
                journal.start_continuation()
                try:
                    async for chunk in upstream.content.iter_any():
                        monitor.on_request_response(next_url, rid, time.time())
                        out = journal.feed_continuation(chunk)
                        if out:
                            await _write(out)
                except (ConnectionResetError, ConnectionError):
                    # Client went away mid-continuation: same obligations
                    # as the primary leg — abort upstream, count it.
                    res_metrics.client_disconnects_total.inc()
                    monitor.on_request_complete(next_url, rid, time.time())
                    upstream.close()
                    span.set_attribute("outcome", "client_disconnect")
                    span.end()
                    return "client_gone"
                except asyncio.CancelledError:
                    if request.transport is None or request.transport.is_closing():
                        res_metrics.client_disconnects_total.inc()
                    monitor.on_request_complete(next_url, rid, time.time())
                    upstream.close()
                    span.set_attribute("outcome", "cancelled")
                    span.end()
                    raise
                monitor.on_request_complete(next_url, rid, time.time())
                if journal.saw_error:
                    # The leg streamed an engine-reported error (now
                    # visible to the client): a deliberate rejection, not
                    # engine ill-health and not a transparent resume.
                    span.set_attribute("outcome", "engine_error")
                    span.end()
                    return "failed"
                if journal.saw_done:
                    _note_success(next_url)
                    span.set_attribute("outcome", "ok")
                    span.end()
                    return "completed"
                # Upstream EOF without [DONE]: this leg died too — feed
                # its breaker and loop for another leg if budget remains.
                _note_failure(next_url, rid, span=span)
                span.set_attribute("outcome", "midstream_death")
                span.end()
                continue
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            monitor.on_request_complete(next_url, rid, time.time())
            _note_failure(next_url, rid, span=span)
            span.set_attribute("error", str(e))
            span.set_attribute("outcome", "midstream_death")
            span.end()
            continue


async def _takeover_stream(
    request: web.Request,
    endpoint: str,
    claimed: dict,
    request_id: str,
    candidates: list,
    deadline: Optional[Deadline],
    request_json: dict,
) -> web.StreamResponse:
    """Resume a dead replica's journaled stream on THIS replica.

    The claimed checkpoint rebuilds the journal (original chunk identity +
    delivered text/token budget) and the standard continuation machinery
    streams the *suffix* to the reconnecting client: duplicate-free,
    original ``id``/``created``, exactly one ``[DONE]``. A stale or
    unusable checkpoint answers with the visible ``stream_truncated``
    contract — the client learns its stream is unrecoverable instead of
    silently receiving a fresh, unrelated generation under the old id.
    """
    trace = request.get("trace") or NOOP_TRACE
    is_chat = endpoint.endswith("/chat/completions")
    response = web.StreamResponse(status=200)
    response.headers["Content-Type"] = "text/event-stream"
    response.headers["Cache-Control"] = "no-cache"
    response.headers["X-Request-Id"] = request_id
    response.headers["X-PST-Stream-Takeover"] = "1"
    await response.prepare(request)

    snap = claimed.get("snap")
    if claimed.get("stale") or not isinstance(snap, dict):
        state_metrics.takeovers_total.labels(outcome="stale").inc()
        res_metrics.stream_truncated_total.labels(reason="takeover_stale").inc()
        trace.add_event("stream_takeover", outcome="stale")
        logger.warning(
            "stream %s: owner replica died but its checkpoint is stale; "
            "terminating visibly", request_id,
        )
        journal = StreamJournal(is_chat, request_json=request_json)
        with contextlib.suppress(Exception):
            await response.write(journal.truncation_tail(
                "owning router replica died and the stream checkpoint is "
                "stale; response truncated"
            ))
            await response.write_eof()
        return response

    journal = StreamJournal.from_snapshot(snap)
    trace.add_event(
        "stream_takeover",
        delivered_tokens=journal.delivered_tokens, legs=journal.legs,
    )
    logger.warning(
        "taking over stream %s from dead replica (%d tokens delivered)",
        request_id, journal.delivered_tokens,
    )
    headers = hop_headers(dict(request.headers), request_id=request_id)
    failover = make_failover(candidates, headers, journal.request_json)
    outcome = await _resume_stream(
        request, response, journal, endpoint, request_id,
        failover, set(), deadline, trace, False, bytearray(),
    )
    if outcome == "completed":
        state_metrics.takeovers_total.labels(outcome="resumed").inc()
        res_metrics.stream_resume_success_total.inc()
    elif outcome != "client_gone":
        state_metrics.takeovers_total.labels(outcome="failed").inc()
        res_metrics.stream_resume_failures_total.inc()
        res_metrics.stream_truncated_total.labels(reason="resume_failed").inc()
        with contextlib.suppress(Exception):
            await response.write(journal.truncation_tail())
    with contextlib.suppress(Exception):
        await response.write_eof()
    return response


# Endpoints that are always hedge-eligible (no streaming mode exists).
_ALWAYS_HEDGEABLE = {"/v1/embeddings", "/v1/rerank", "/v1/score"}


def hedge_eligible(endpoint: str, request_json: Optional[dict]) -> bool:
    """Only non-streaming idempotent work may be hedged: a duplicate
    completion/embedding is wasted compute, never wrong output — but a
    stream is committed to one upstream after the first byte, and
    mutating/admin endpoints must not execute twice."""
    if endpoint in ("/v1/completions", "/v1/chat/completions"):
        return not (request_json or {}).get("stream")
    return endpoint in _ALWAYS_HEDGEABLE


async def _buffered_attempt(
    request: web.Request,
    url: str,
    endpoint: str,
    body: bytes,
    request_id: str,
    deadline: Optional[Deadline],
    suffix: str = "",
    span_name: str = "proxy_attempt",
    kind: str = "primary",
):
    """One fully-buffered upstream attempt (hedge path only — hedged
    endpoints are all non-streaming, so buffering is safe and lets the
    first *usable* response win the race). Returns
    ``(status, headers, payload, url)``; raises on transport failure.
    Feeds the breakers and request-stats monitor like any proxy attempt.
    Each leg is its own span (``proxy_attempt`` for primary/retry legs,
    ``hedge`` for the hedge leg) carrying the same trace id downstream.
    """
    session: aiohttp.ClientSession = request.app["client_session"]
    policy = get_retry_policy()
    monitor = get_request_stats_monitor()
    trace = request.get("trace") or NOOP_TRACE
    rid = request_id + suffix
    span = trace.span(
        span_name,
        attributes={"server": url, "kind": kind, "endpoint": endpoint},
    )
    fwd = _trace_headers(
        with_deadline_header(_forwardable(request.headers), deadline),
        request_id, span,
    )
    fwd.update(_tenant_headers(request))
    remaining = deadline.remaining_s() if deadline is not None else None
    timeout = aiohttp.ClientTimeout(
        total=max(remaining, 0.001) if remaining is not None else None,
        connect=(policy.connect_timeout or None) if policy else None,
        sock_read=(policy.read_timeout or None) if policy else None,
    )
    monitor.on_new_request(url, rid, time.time())
    try:
        async with session.request(
            request.method, url + endpoint, data=body, headers=fwd,
            timeout=timeout,
        ) as upstream:
            payload = await upstream.read()
            status = upstream.status
            headers = {
                k: v for k, v in upstream.headers.items()
                if k.lower() not in _HOP_HEADERS
            }
    except asyncio.CancelledError:
        # The race was decided against this attempt: closing the request
        # aborts it upstream (the engine stops decoding for a loser).
        monitor.on_request_complete(url, rid, time.time())
        span.set_attribute("outcome", "cancelled")
        span.end()
        raise
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        monitor.on_request_complete(url, rid, time.time())
        span.set_attribute("error", str(e))
        if not (deadline is not None and deadline.expired()):
            _note_failure(url, rid, span=span)
            span.set_attribute("outcome", "error")
        else:
            span.set_attribute("outcome", "deadline_shed")
        span.end()
        raise
    monitor.on_request_response(url, rid, time.time())
    monitor.on_request_complete(url, rid, time.time())
    span.set_attribute("http.status_code", status)
    if status == 503 and "X-PST-Draining" in headers:
        get_service_discovery().set_draining(url, True)
        span.set_attribute("outcome", "draining")
    elif status == 503 and "X-PST-Warming" in headers:
        get_service_discovery().set_warming(url, True)
        span.set_attribute("outcome", "warming")
    elif status == 503 and "X-PST-Sleeping" in headers:
        # A hedge/race attempt hit a slept standby: wake it for future
        # traffic (the racing primary serves this request).
        get_service_discovery().set_warming(url, True)
        spawn_owned(_fire_wake(session, url), name=f"wake:{url}")
        span.set_attribute("outcome", "sleeping")
    elif status == 504 and DEADLINE_EXCEEDED_HEADER in headers:
        span.set_attribute("outcome", "deadline_shed")
        trace.add_event("deadline_shed", stage="engine", server=url)
    elif status >= 500:
        _note_failure(url, rid, span=span)
        span.set_attribute("outcome", "error_passthrough")
    else:
        _note_success(url)
        span.set_attribute("outcome", "ok")
    span.end()
    return status, headers, payload, url


def _attempt_result(task: asyncio.Task):
    """Result of a done attempt task, or None if it failed/was cancelled."""
    if task.cancelled() or task.exception() is not None:
        return None
    return task.result()


async def proxy_with_hedge(
    request: web.Request,
    backend_url: str,
    endpoint: str,
    body: bytes,
    request_id: str,
    failover: FailoverFn,
    deadline: Optional[Deadline],
) -> web.StreamResponse:
    """Tail-latency hedging ("The Tail at Scale") for non-streaming
    idempotent requests: race the primary against one hedge attempt fired
    after ``HedgePolicy.delay_s()``; first usable response wins, the loser
    is cancelled upstream. The hedge pick goes through the same routing +
    breaker path as a failover (a half-open breaker's probe slot IS the
    hedge), never fires at an open breaker, and is capped by the hedge
    budget so hedging cannot double fleet load during an incident."""
    hedge = get_hedge_policy()
    registry = get_breaker_registry()
    policy = get_retry_policy()
    start = time.time()
    hedge.note_request_start()
    hedge_acquired = False
    tried = {backend_url}
    primary = asyncio.ensure_future(
        _buffered_attempt(request, backend_url, endpoint, body, request_id, deadline)
    )
    hedge_task: Optional[asyncio.Task] = None

    async def _one_failover(failed_result) -> web.StreamResponse:
        """Single failover after a failed attempt — plain retry semantics
        (same gates as proxy_and_stream: ``--proxy-retries``, deadline
        budget, tagged-504 pass-through), NOT a hedge."""
        if failed_result is not None and (
            failed_result[0] == 504
            and DEADLINE_EXCEEDED_HEADER in failed_result[1]
        ):
            # The engine shed the budget deliberately: pass through, never
            # replay work whose budget is gone downstream.
            return _hedge_failure_response(failed_result, request_id)
        if policy is not None and not policy.should_retry(0):
            return _hedge_failure_response(failed_result, request_id)
        if deadline is not None and deadline.expired():
            return _deadline_response(
                "deadline exceeded after upstream failure", "router_proxy",
                trace=request.get("trace"), request_id=request_id,
            )
        if _deadline_blocks_attempt(deadline):
            res_metrics.deadline_sheds_total.labels(stage="router_retry").inc()
            return _hedge_failure_response(failed_result, request_id)
        alt = await failover(tried)
        if alt is None:
            return _hedge_failure_response(failed_result, request_id)
        res_metrics.retries_total.labels(server=backend_url).inc()
        res_metrics.failovers_total.inc()
        tried.add(alt)
        try:
            r = await _buffered_attempt(
                request, alt, endpoint, body, request_id, deadline,
                suffix="-retry", kind="retry",
            )
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            if deadline is not None and deadline.expired():
                return _deadline_response(
                    "deadline exceeded during failover attempt", "router_proxy",
                    trace=request.get("trace"), request_id=request_id,
                )
            return _error_response(502, f"backend error: {e}", "bad_gateway",
                                       request_id=request_id)
        return await _hedge_respond(request, endpoint, request_id, r)

    try:
        delay = hedge.delay_s()
        if deadline is not None:
            # Firing a hedge the budget can't cover is pure waste: leave at
            # least one connect-floor of budget for the hedge attempt.
            delay = min(
                delay,
                max(deadline.remaining_s() - min_attempt_budget(policy), 0.0),
            )
        done, _ = await asyncio.wait({primary}, timeout=delay)

        if done:
            result = _attempt_result(primary)
            if result is not None and result[0] < 500:
                hedge.observe_latency(time.time() - start)
                return await _hedge_respond(request, endpoint, request_id, result)
            # Primary failed before the hedge delay elapsed: plain failover.
            return await _one_failover(result)

        # Primary still in flight after the hedge delay: try to hedge.
        suppressed = None
        alt_url = await failover(tried)
        if alt_url is None:
            suppressed = "no_candidate"
        elif (
            registry is not None
            and registry.get(alt_url).current_state() is BreakerState.OPEN
        ):
            # route_with_resilience fails open during a fleet-wide brownout;
            # a hedge is optional work and must NOT ride that exception.
            suppressed = "breaker"
        elif _deadline_blocks_attempt(deadline):
            suppressed = "budget"
        elif not hedge.try_acquire_hedge():
            suppressed = "capacity"
        if suppressed is not None:
            res_metrics.hedges_suppressed_total.labels(reason=suppressed).inc()
            (request.get("trace") or NOOP_TRACE).add_event(
                "hedge_suppressed", reason=suppressed
            )
            try:
                result = await primary
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                if deadline is not None and deadline.expired():
                    return _deadline_response(
                        "deadline exceeded during upstream attempt",
                        "router_proxy", trace=request.get("trace"),
                        request_id=request_id,
                    )
                return await _one_failover(None)
            if result[0] >= 500:
                # A suppressed hedge must not also lose the failover the
                # plain proxy path would have run.
                return await _one_failover(result)
            hedge.observe_latency(time.time() - start)
            return await _hedge_respond(request, endpoint, request_id, result)

        hedge_acquired = True
        tried.add(alt_url)
        res_metrics.hedges_fired_total.inc()
        trace = request.get("trace") or NOOP_TRACE
        trace.add_event("hedge_fired", server=alt_url,
                        delay_ms=round(delay * 1000.0, 1))
        logger.info(
            "hedging %s: primary %s slow (>%.0fms), firing hedge to %s",
            request_id, backend_url, delay * 1000, alt_url,
        )
        hedge_task = asyncio.ensure_future(
            _buffered_attempt(
                request, alt_url, endpoint, body, request_id, deadline,
                suffix="-hedge", span_name="hedge", kind="hedge",
            )
        )
        pending = {primary, hedge_task}
        winner = None
        winner_is_hedge = False
        while pending and winner is None:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for t in done:
                r = _attempt_result(t)
                if r is not None and r[0] < 500:
                    winner = r
                    winner_is_hedge = t is hedge_task
                    break
        for t in pending:
            t.cancel()
            if t is hedge_task:
                res_metrics.hedges_cancelled_total.inc()
        if winner is None:
            if deadline is not None and deadline.expired():
                return _deadline_response(
                    "deadline exceeded (primary and hedge)", "router_proxy",
                    trace=request.get("trace"), request_id=request_id,
                )
            last = _attempt_result(primary) or (
                _attempt_result(hedge_task) if hedge_task.done() else None
            )
            # Both primary and hedge failed: the plain proxy path would
            # still have retry budget — honor it with one more failover.
            return await _one_failover(last)
        if winner_is_hedge:
            res_metrics.hedges_won_total.inc()
            trace.add_event("hedge_won", server=winner[3])
        hedge.observe_latency(time.time() - start)
        return await _hedge_respond(
            request, endpoint, request_id, winner, hedged=winner_is_hedge
        )
    finally:
        hedge.note_request_end()
        if hedge_acquired:
            hedge.release_hedge()
        for t in (primary, hedge_task):
            if t is not None and not t.done():
                t.cancel()


def _hedge_failure_response(
    result, request_id: Optional[str] = None
) -> web.Response:
    """Both attempts failed: pass the last 5xx through unchanged — headers
    included, so tagged sheds (X-PST-Deadline-Exceeded) survive — same rule
    as proxy_and_stream with nowhere left to go; else a generic 502."""
    if result is not None:
        status, headers, payload, _ = result
        resp = web.Response(body=payload, status=status)
        for k, v in headers.items():
            resp.headers[k] = v
        return resp
    return _error_response(502, "all upstream attempts failed", "bad_gateway",
                           request_id=request_id)


async def _hedge_respond(
    request: web.Request,
    endpoint: str,
    request_id: str,
    result,
    hedged: bool = False,
) -> web.Response:
    """Materialize the winning buffered attempt as the client response,
    running the same post-response hooks (semantic cache store, callbacks)
    the streaming path runs."""
    status, headers, payload, url = result
    resp = web.Response(body=payload, status=status)
    for k, v in headers.items():
        resp.headers[k] = v
    resp.headers["X-Request-Id"] = request_id
    resp.headers["X-PST-Hedge"] = "won" if hedged else "primary"
    if status < 400:
        callback = get_custom_callback_handler()
        semantic_store = request.app.get("semantic_cache_store")
        parsed = request.get("parsed_json") or {}
        if (
            semantic_store is not None
            and endpoint == "/v1/chat/completions"
            and not parsed.get("stream")
        ):
            try:
                await semantic_store(request, payload)
            except Exception as e:  # noqa: BLE001
                logger.debug("semantic cache store failed: %s", e)
        if callback is not None and callback.post_request is not None:
            try:
                await callback.call_post_request(request, payload)
            except Exception as e:  # noqa: BLE001
                logger.error("post_request callback failed: %s", e)
    return resp


async def route_general_request(request: web.Request, endpoint: str) -> web.StreamResponse:
    """Route an OpenAI-API request to an engine and stream the response."""
    # The tracing middleware assigned the id (and opened the root span);
    # fall back for paths it does not cover so the id is never absent.
    request_id = (
        request.get("request_id")
        or request.headers.get("X-Request-Id")
        or str(uuid.uuid4())
    )
    trace = request.get("trace") or NOOP_TRACE
    # End-to-end budget: parsed by the admission middleware (anchored at
    # arrival, so queue time counts), or here for paths it does not cover.
    deadline: Optional[Deadline] = request.get("deadline")
    if deadline is None:
        deadline = parse_deadline(request.headers, get_default_deadline_ms())
        if deadline is not None:  # path the admission middleware skipped
            res_metrics.deadline_budget_ms.observe(
                max(deadline.remaining_ms(), 0.0)
            )
    if deadline is not None and deadline.expired():
        # Cheapest shed point: nothing has been parsed, routed, or sent.
        return _deadline_response(
            "deadline exceeded before routing", "router_admission",
            trace=trace, request_id=request_id,
        )
    body = await request.read()
    try:
        request_json = json.loads(body) if body else {}
    except json.JSONDecodeError:
        return _error_response(400, "invalid JSON in request body",
                               request_id=request_id)
    request["parsed_json"] = request_json  # for post-response hooks

    callback = get_custom_callback_handler()
    if callback is not None:
        short = await callback.call_pre_request(request, body, request_json)
        if short is not None:
            return short

    # PII gate (experimental, feature-gated).
    pii_check = request.app.get("pii_check")
    if pii_check is not None:
        blocked = await pii_check(request_json)
        if blocked is not None:
            return blocked

    discovery = get_service_discovery()
    endpoints = discovery.get_endpoint_info()

    requested_model = request_json.get("model", "")
    aliases = getattr(discovery, "aliases", None) or {}
    if requested_model in aliases:
        requested_model = aliases[requested_model]
        request_json["model"] = requested_model
        body = json.dumps(request_json).encode()

    # Rewriter hook (after alias resolution, before routing).
    rewriter = get_request_rewriter()
    rewritten = rewriter.rewrite_request(body.decode(), requested_model, endpoint)
    if rewritten != body.decode():
        body = rewritten.encode()
        request_json = json.loads(rewritten)
    # The store hook (proxy_and_stream) keys off parsed_json — keep it the
    # same dict the cache probe below sees, or check/store keys diverge.
    request["parsed_json"] = request_json

    # Semantic cache probe (experimental): a hit short-circuits routing
    # entirely (reference main_router.py:47-54 check_semantic_cache). Runs
    # after alias resolution + rewriting so cache lookups and stores key on
    # the same (resolved) model string and final message content.
    cache_check = request.app.get("semantic_cache_check")
    if cache_check is not None and endpoint == "/v1/chat/completions":
        cached = await cache_check(request_json)
        if cached is not None:
            return cached

    router = get_routing_logic()

    # Debug escape hatch: pin a specific engine by id with ?id=...
    pinned_id = request.query.get("id")
    if pinned_id:
        candidates = [e for e in endpoints if e.Id == pinned_id]
    elif isinstance(router, DisaggregatedPrefillRouter):
        # P/D pools serve under distinct labels; model filter happens per-pool.
        candidates = [e for e in endpoints if not e.sleep]
    else:
        candidates = [
            e for e in endpoints if (e.has_model(requested_model) and not e.sleep)
        ]
        if not candidates:
            # Scale-to-zero (docs/autoscaling.md "Scale to zero"): a pool
            # parked at a single slept standby has no routable engine — the
            # first arrival must WAKE it, not 404. Slept matches become
            # candidates; the proxy's tagged-503 path fires /wake_up and
            # holds the request through the wake.
            candidates = [e for e in endpoints if e.has_model(requested_model)]
    # Disagg is the fleet SHAPE, not just a routing policy
    # (docs/disagg.md): the two-leg flow engages for the legacy
    # label-split policy AND whenever THIS MODEL's serving set declares
    # both a prefill and a decode pool — generation endpoints only (a
    # pool split means nothing to embeddings/rerank), and another
    # model's pools must never drag a fused-only model through the
    # two-leg flow (its prefill would simply run twice).
    is_disagg = isinstance(router, DisaggregatedPrefillRouter) or (
        endpoint in ("/v1/completions", "/v1/chat/completions")
        and disagg.fleet_has_pools(candidates)
    )
    if not candidates:
        return _error_response(
            404,
            f"model {requested_model!r} not found on any live engine",
            "not_found_error",
            request_id=request_id,
        )

    # Router HA takeover (docs/router-ha.md): a client whose streaming
    # request died with its owning replica retries it — same body, same
    # X-Request-Id — through the load balancer and lands here. If a live
    # journal checkpoint for that id is claimable (its owner is DEAD),
    # this replica resumes the stream from the checkpoint: the reply
    # carries only the un-delivered suffix, spliced under the original
    # chunk identity by PR 4's continuation machinery. A stale checkpoint
    # terminates visibly (``stream_truncated``) instead of guessing.
    if (
        not pinned_id
        and not is_disagg
        and endpoint in ("/v1/completions", "/v1/chat/completions")
        and request_json.get("stream")
    ):
        ha_backend = _shared_state_backend()
        if ha_backend is not None:
            claimed = ha_backend.claim_remote_journal(request_id)
            if claimed is not None:
                return await _takeover_stream(
                    request, endpoint, claimed, request_id, candidates,
                    deadline, request_json,
                )

    if pinned_id:
        # An explicit pin is a debug escape hatch: bypass the routing policy
        # AND the resilience filters (breakers, drain) so an operator can
        # always reach the exact engine they asked for — and no failover,
        # which would silently re-route off the pinned engine. The deadline
        # still propagates (the engine sheds expired work regardless).
        return await proxy_and_stream(
            request, candidates[0].url, endpoint, body, request_id,
            deadline=deadline,
        )

    if is_disagg:
        return await route_disaggregated_prefill_request(
            request, endpoint, request_json, candidates, request_id,
            deadline=deadline,
        )

    engine_stats = get_engine_stats_scraper().get_engine_stats()
    request_stats = get_request_stats_monitor().get_request_stats(time.time())
    # The routing decision is its own stage: which engine, picked by which
    # policy, from how many live candidates.
    routing_span = trace.span(
        "routing",
        attributes={
            "policy": type(router).__name__,
            "candidates": len(candidates),
            "model": requested_model,
        },
    )
    # Routing-time hops (the KV controller /lookup) relay these headers:
    # the ROUTER-assigned request id and the routing span must be on them
    # — clients usually send neither X-Request-Id nor traceparent.
    headers = hop_headers(
        dict(request.headers), request_id=request_id, span=routing_span
    )
    # Routing sees the resolved tenant class too (fleet scoring demotes
    # batch-tier work from pinning/evicting interactive affinity).
    headers.update(_tenant_headers(request))
    try:
        backend_url = await route_with_resilience(
            router, candidates, engine_stats, request_stats, headers, request_json
        )
    except ValueError as e:
        routing_span.set_attribute("outcome", "no_backend")
        routing_span.end()
        return _error_response(503, f"no backend available: {e}",
                               "service_unavailable", request_id=request_id)
    routing_span.set_attribute("engine", backend_url)
    routing_span.set_attribute("outcome", "routed")
    routing_span.end()
    # The one access-log-shaped line per request: INFO under --log-format
    # json, where it carries the bound trace/request/tenant context
    # (docs/observability.md "Structured logging") AND the hot-path
    # sampler bounds its volume; DEBUG in text mode, where no sampler is
    # installed and an unbounded per-request INFO line would be a log
    # regression for existing deployments.
    logger.log(
        logging.INFO if structured_logging_active() else logging.DEBUG,
        "routing %s for model %s to %s",
        request_id, requested_model, backend_url,
    )
    failover = make_failover(candidates, headers, request_json)
    hedge = get_hedge_policy()
    if (
        hedge is not None
        and hedge.enabled
        and hedge_eligible(endpoint, request_json)
    ):
        return await proxy_with_hedge(
            request, backend_url, endpoint, body, request_id, failover,
            deadline,
        )
    return await proxy_and_stream(
        request, backend_url, endpoint, body, request_id,
        failover=failover, deadline=deadline,
    )


async def _disagg_prefill_leg(
    request: web.Request,
    endpoint: str,
    prefill_json: dict,
    candidates: list,
    prefill_url: str,
    request_id: str,
    deadline: Optional[Deadline],
    trace,
    headers: dict,
) -> dict:
    """The prefill leg: retry/failover across the prefill pool, same
    per-attempt bounds as ``proxy_and_stream`` (nothing from the prefill
    response reaches the client, so re-routing is always safe).

    Returns ``{"ok", "url", "error", "shed", "done_at"}`` — under overlap
    the caller treats this as a *completion signal* (a failure means the
    decode engine's prefetch will time out into its fused recompute, not
    a client error); the serial path turns failures into responses."""
    monitor = get_request_stats_monitor()
    session: aiohttp.ClientSession = request.app["client_session"]
    policy = get_retry_policy()
    failover = make_failover(candidates, headers, prefill_json)
    tried = {prefill_url}
    attempt = 0
    while True:
        if deadline is not None and deadline.expired():
            return {"ok": False, "url": prefill_url, "error": None,
                    "shed": True, "done_at": time.monotonic()}
        prefill_span = trace.span(
            "disagg_prefill", attributes={"server": prefill_url}
        )
        # Without the timeout a black-holed prefill engine would hang the
        # leg forever with the breaker never fed. The leg is
        # non-streaming, so the remaining budget bounds the whole attempt.
        remaining = deadline.remaining_s() if deadline is not None else None
        attempt_timeout = aiohttp.ClientTimeout(
            total=max(remaining, 0.001) if remaining is not None else None,
            connect=(policy.connect_timeout or None) if policy else None,
            sock_read=(policy.read_timeout or None) if policy else None,
        )
        fwd_headers = _trace_headers(
            with_deadline_header(_forwardable(headers), deadline),
            request_id, prefill_span,
        )
        t_prefill_start = time.time()
        monitor.on_new_request(prefill_url, f"{request_id}-prefill", t_prefill_start)
        error: Optional[str] = None
        draining = False
        warming = False
        try:
            async with session.post(
                prefill_url + endpoint, json=prefill_json,
                headers=fwd_headers, timeout=attempt_timeout,
            ) as resp:
                draining = resp.status == 503 and "X-PST-Draining" in resp.headers
                warming = resp.status == 503 and "X-PST-Warming" in resp.headers
                if not draining and not warming:
                    resp.raise_for_status()
                    await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            error = str(e)
        if error is None and not draining and not warming:
            monitor.on_request_response(prefill_url, f"{request_id}-prefill", time.time())
            monitor.on_request_complete(prefill_url, f"{request_id}-prefill", time.time())
            _note_success(prefill_url)
            prefill_span.set_attribute("outcome", "ok")
            prefill_span.end()
            logger.debug(
                "disagg prefill for %s done in %.3fs",
                request_id, time.time() - t_prefill_start,
            )
            return {"ok": True, "url": prefill_url, "error": None,
                    "shed": False, "done_at": time.monotonic()}
        monitor.on_request_complete(prefill_url, f"{request_id}-prefill", time.time())
        if error is not None:
            prefill_span.set_attribute("error", error)
        if draining:
            # Deliberate drain, not a failure (same rule as
            # proxy_and_stream): reconcile discovery, spare the breaker.
            get_service_discovery().set_draining(prefill_url, True)
            prefill_span.set_attribute("outcome", "draining")
        elif warming:
            # Warming precompile pass — same rule: unroutable, no breaker.
            get_service_discovery().set_warming(prefill_url, True)
            prefill_span.set_attribute("outcome", "warming")
        elif deadline is not None and deadline.expired():
            # Budget exhausted mid-prefill: a deadline shed, not a failure.
            prefill_span.set_attribute("outcome", "deadline_shed")
            prefill_span.end()
            return {"ok": False, "url": prefill_url, "error": None,
                    "shed": True, "done_at": time.monotonic()}
        else:
            _note_failure(prefill_url, request_id, span=prefill_span)
            prefill_span.set_attribute("outcome", "error")
        prefill_span.end()
        backoff = policy.backoff(attempt) if policy else 0.0
        if _deadline_blocks_attempt(deadline, backoff):
            res_metrics.deadline_sheds_total.labels(stage="router_retry").inc()
            next_url = None
        else:
            next_url = await _next_backend(failover, tried, attempt)
        if next_url is None:
            return {"ok": False, "url": prefill_url,
                    "error": error or "engine draining", "shed": False,
                    "done_at": time.monotonic()}
        logger.warning(
            "prefill engine %s failed for %s (%s); failing over to %s",
            prefill_url, request_id, error or "draining", next_url,
        )
        res_metrics.retries_total.labels(server=prefill_url).inc()
        res_metrics.failovers_total.inc()
        await asyncio.sleep(policy.backoff(attempt))
        attempt += 1
        prefill_url = next_url
        tried.add(prefill_url)


async def route_disaggregated_prefill_request(
    request: web.Request,
    endpoint: str,
    request_json: dict,
    endpoints: list,
    request_id: str,
    deadline: Optional[Deadline] = None,
) -> web.StreamResponse:
    """Two-leg disagg flow with streamed KV handoff (docs/disagg.md).

    With overlap on (the default) the decode leg dispatches CONCURRENTLY
    with the prefill leg: the prefill engine publishes each chunk's KV
    pages to the remote block store as the chunk completes, the decode
    engine follows the request's manifest and prefetches them while the
    prefill is still running, and the first decode step dispatches as
    soon as the final block lands — the prefill response is a completion
    *signal*, not a gate. Transfer failure at any point degrades to the
    fused path (the serving engine recomputes the prefill) with no
    client-visible error, counted in ``pst_disagg_fallback_total``.

    The deadline spans both legs: each leg forwards the remaining budget
    per attempt, and a budget that dies between the legs sheds with a
    tagged 504 before the decode leg dispatches.
    """
    router = get_routing_logic()
    engine_stats = get_engine_stats_scraper().get_engine_stats()
    request_stats = get_request_stats_monitor().get_request_stats(time.time())
    trace = request.get("trace") or NOOP_TRACE
    # Same relay contract as route_general_request: routing-time hops see
    # the router-assigned id (the per-pool routing spans parent their own
    # outbound attempts below). Both legs inherit the tenant stamp.
    headers = hop_headers(dict(request.headers), request_id=request_id)
    headers.update(_tenant_headers(request))

    # Pool split (docs/disagg.md): each leg routes within its declared
    # pool plus the fused engines; an empty pool degrades to the whole
    # candidate list so mixed fleets keep serving.
    prefill_candidates = disagg.pool_candidates(endpoints, disagg.POOL_PREFILL)
    # Decode leg prefers engines whose remote-KV tier is healthy: scraped
    # fallback + integrity-failure counters bias (stable sort — never
    # exclude) the leg away from engines stuck recomputing transfers.
    decode_candidates = disagg.order_by_kv_health(
        disagg.pool_candidates(endpoints, disagg.POOL_DECODE), engine_stats
    )

    original_max_tokens = request_json.get("max_tokens")
    original_stream = request_json.get("stream", False)
    prefill_json = dict(request_json)
    prefill_json["max_tokens"] = 1
    prefill_json["stream"] = False
    # Ask the engine to retain/publish KV for this request id so the decode
    # engine can fetch it (kv_transfer_params mirrors the reference's
    # connector config surface, deployment-vllm-multi.yaml:180-189) — the
    # producer role makes the engine's streamed publisher ship each
    # prefill chunk's pages under this id as the chunk completes.
    prefill_json["kv_transfer_params"] = {
        "request_id": request_id, "role": "producer", "pool": "prefill",
    }

    routing_span = trace.span(
        "routing", attributes={"pool": "prefill",
                               "policy": type(router).__name__}
    )
    try:
        prefill_url = await route_with_resilience(
            router, prefill_candidates, engine_stats, request_stats, headers,
            prefill_json,
        )
    except ValueError as e:
        routing_span.set_attribute("outcome", "no_backend")
        routing_span.end()
        return _error_response(503, f"no prefill backend: {e}",
                               "service_unavailable", request_id=request_id)
    routing_span.set_attribute("engine", prefill_url)
    routing_span.end()

    decode_json = dict(request_json)
    if original_max_tokens is not None:
        decode_json["max_tokens"] = original_max_tokens
    decode_json["stream"] = original_stream
    decode_json["kv_transfer_params"] = {
        "request_id": request_id, "role": "consumer", "pool": "decode",
        "prefill_url": prefill_url,
    }
    routing_span = trace.span(
        "routing", attributes={"pool": "decode",
                               "policy": type(router).__name__}
    )
    try:
        decode_url = await route_with_resilience(
            router, decode_candidates, engine_stats, request_stats, headers,
            decode_json,
        )
    except ValueError:
        # No routable decode pool: serve the request FUSED on the prefill
        # pool (it holds the model too) — degradation, not a 503.
        routing_span.set_attribute("outcome", "no_backend")
        routing_span.end()
        disagg.fallback_total.labels(reason="no_decode_backend").inc()
        fused_json = dict(request_json)
        fused_json.pop("kv_transfer_params", None)
        return await proxy_and_stream(
            request, prefill_url, endpoint,
            json.dumps(fused_json).encode(), request_id,
            debug_headers={"X-Disagg-Fallback": "no_decode_backend"},
            failover=make_failover(prefill_candidates, headers, fused_json),
            deadline=deadline,
        )
    routing_span.set_attribute("engine", decode_url)
    routing_span.end()

    # Decode-leg failover list: the decode pool first, then the prefill
    # engine as the last resort — it holds the freshly computed KV
    # resident, so serving the full request there IS the fused path.
    decode_failover = list(decode_candidates)
    if all(e.url != prefill_url for e in decode_failover):
        decode_failover += [e for e in endpoints if e.url == prefill_url]

    overlap_enabled = bool(
        getattr(request.app.get("args"), "disagg_overlap", True)
    )
    serial_outcome: Optional[dict] = None
    prefill_task: Optional[asyncio.Task] = None
    t_prefill_dispatch = time.monotonic()
    if overlap_enabled:
        # THE overlap: the prefill leg becomes a concurrent task whose
        # response is a completion signal; the decode leg dispatches NOW
        # and prefetches the streamed KV while the prefill runs.
        prefill_task = spawn_owned(
            _disagg_prefill_leg(
                request, endpoint, prefill_json, prefill_candidates,
                prefill_url, request_id, deadline, trace, headers,
            ),
            name=f"disagg-prefill:{request_id}",
        )
    else:
        serial_outcome = await _disagg_prefill_leg(
            request, endpoint, prefill_json, prefill_candidates,
            prefill_url, request_id, deadline, trace, headers,
        )
        if serial_outcome["shed"]:
            return _deadline_response(
                "deadline exceeded during prefill", "router_proxy",
                trace=trace, request_id=request_id,
            )
        if not serial_outcome["ok"]:
            disagg.fallback_total.labels(reason="prefill_error").inc()
            return _error_response(
                502, f"prefill failed: {serial_outcome['error']}",
                "bad_gateway", request_id=request_id,
            )
        disagg.transfer_seconds.observe(
            max(serial_outcome["done_at"] - t_prefill_dispatch, 0.0)
        )
        # Serial flow: zero overlap by construction (the old gate).
        disagg.overlap_seconds.observe(0.0)

    # Budget died between the legs (or while the overlap was being set
    # up): shed with the tagged 504 before dispatching the decode leg.
    if deadline is not None and deadline.expired():
        if prefill_task is not None:
            prefill_task.cancel()
        disagg.fallback_total.labels(reason="deadline").inc()
        return _deadline_response(
            "deadline exceeded between disagg legs", "router_proxy",
            trace=trace, request_id=request_id,
        )

    t_decode_dispatch = time.monotonic()
    try:
        return await proxy_and_stream(
            request,
            decode_url,
            endpoint,
            json.dumps(decode_json).encode(),
            request_id,
            debug_headers={"X-Prefill-Url": prefill_url,
                           "X-Decode-Url": decode_url},
            failover=make_failover(decode_failover, headers, decode_json),
            deadline=deadline,
        )
    finally:
        if prefill_task is not None:
            # Completion signal, not a gate: the decode response is done
            # (or the client left) — collect the prefill outcome with a
            # bounded wait so a hung leg can never pin this handler.
            try:
                outcome = await asyncio.wait_for(
                    asyncio.shield(prefill_task), timeout=30.0
                )
            except asyncio.CancelledError:
                # The handler itself is being torn down (client gone):
                # release the leg and let the cancellation propagate.
                prefill_task.cancel()
                raise
            except (asyncio.TimeoutError, Exception) as e:  # noqa: BLE001
                prefill_task.cancel()
                logger.warning(
                    "disagg prefill leg for %s did not complete: %s",
                    request_id, e,
                )
                outcome = None
            if outcome is not None:
                disagg.transfer_seconds.observe(
                    max(outcome["done_at"] - t_prefill_dispatch, 0.0)
                )
                # >0 means the decode leg was in flight before the
                # prefill response returned — decode started before
                # prefill finished, the number the tentpole is about.
                disagg.overlap_seconds.observe(
                    max(outcome["done_at"] - t_decode_dispatch, 0.0)
                )
                if not outcome["ok"]:
                    # The decode engine's prefetch times out into its
                    # fused recompute; the client saw no error. A budget
                    # death inside the leg is a shed, not engine failure
                    # — it keeps its own reason.
                    disagg.fallback_total.labels(
                        reason="deadline" if outcome["shed"]
                        else "prefill_error"
                    ).inc()


async def _admin_fanout(targets, call) -> dict:
    """Run ``call(ep)`` against every target engine concurrently. One
    engine's failure becomes an ``{"error": ...}`` entry instead of failing
    the whole fan-out — and a blocking call (drain ``wait=1``) costs max
    one timeout, not one per engine."""

    async def one(ep):
        try:
            return ep.url, await call(ep)
        except (aiohttp.ClientError, OSError) as e:
            return ep.url, {"error": str(e)}

    return dict(await asyncio.gather(*(one(ep) for ep in targets)))


async def route_sleep_wakeup_request(request: web.Request, action: str) -> web.Response:
    """Admin proxy for /sleep, /wake_up, /is_sleeping across engines.

    Targets engines by ``model`` query-param label (or all engines when
    omitted), mirroring reference ``request.py:437-513``; ``url`` targets
    one specific engine — the operator's scale-to-zero path
    (docs/autoscaling.md "Scale to zero") sleeps exactly one standby.
    """
    discovery = get_service_discovery()
    endpoints = discovery.get_endpoint_info()
    label = request.query.get("model")
    url = request.query.get("url")
    targets = [
        e for e in endpoints
        if (url is None or e.url == url)
        and (label is None or e.model_label == label or label in e.model_names)
    ]
    if not targets:
        return _error_response(404, f"no engines matching {url or label!r}",
                               "not_found_error",
                               request_id=request.get("request_id"))
    session: aiohttp.ClientSession = request.app["client_session"]
    # Admin credentials pass through; the hop trio rides along so engine
    # logs join the admin action to the request that triggered it.
    headers = _trace_headers(
        _forwardable(request.headers), request.get("request_id") or "", None
    )

    async def call(ep):
        if action == "is_sleeping":
            async with session.get(
                f"{ep.url}/is_sleeping", headers=headers
            ) as resp:
                return await resp.json()
        level = request.query.get("level")
        params = {"level": level} if level else None
        if action == "sleep":
            # Unroutable BEFORE the engine acks: same ordering as the
            # drain fan-out — no request may race into a standby that is
            # about to stop serving (docs/autoscaling.md "Scale to zero").
            discovery.set_sleeping(ep.url, True)
        async with session.post(
            f"{ep.url}/{action}", params=params, headers=headers
        ) as resp:
            status = resp.status
        if action == "sleep" and status >= 400:
            discovery.set_sleeping(ep.url, False)  # engine refused: restore
        elif action == "wake_up" and status < 400:
            # Routable again; if the wake re-enters warmup the engine's
            # tagged 503 re-marks it warming on first contact.
            discovery.set_sleeping(ep.url, False)
        return {"status": status}

    return web.json_response(await _admin_fanout(targets, call))


async def route_drain_request(request: web.Request, action: str) -> web.Response:
    """Admin proxy for engine drain: POST /drain, POST /undrain,
    GET /is_draining — fanned out like sleep/wake, by ``model`` label or to
    a single engine via ``?url=``."""
    discovery = get_service_discovery()
    endpoints = discovery.get_endpoint_info()
    label = request.query.get("model")
    url_filter = request.query.get("url")
    targets = [
        e for e in endpoints
        if (label is None or e.model_label == label or label in e.model_names)
        and (url_filter is None or e.url == url_filter)
    ]
    if not targets:
        return _error_response(404, "no engines matching filter",
                               "not_found_error",
                               request_id=request.get("request_id"))
    session: aiohttp.ClientSession = request.app["client_session"]
    # Forward the caller's headers (Authorization in particular): engines
    # behind --api-key guard /drain, and the router holds no engine
    # credentials of its own. The hop trio rides along.
    headers = _trace_headers(
        _forwardable(request.headers), request.get("request_id") or "", None
    )

    async def call(ep):
        if action == "is_draining":
            async with session.get(
                f"{ep.url}/is_draining", headers=headers
            ) as resp:
                return await resp.json()
        # Forward wait/timeout so a blocking drain works through the
        # router exactly as it does against the engine directly.
        params = {
            k: request.query[k] for k in ("wait", "timeout")
            if k in request.query
        }
        # Mark discovery up front rather than waiting for the response or
        # the next probe/watch cycle: the engine flips state the moment it
        # receives the POST, and a blocking drain (wait=1) holds the
        # response until in-flight work finishes — the lag window would
        # keep routing to the draining engine and count its deliberate
        # 503s as breaker failures. Reverted below if the call fails.
        if action == "drain":
            discovery.set_draining(ep.url, True)
        try:
            async with session.post(
                f"{ep.url}/{action}", params=params or None, headers=headers
            ) as resp:
                ok = resp.status == 200
                result = await resp.json()
        except (aiohttp.ClientError, OSError):
            if action == "drain":
                discovery.set_draining(ep.url, False)
            raise
        if action == "drain" and not ok:
            discovery.set_draining(ep.url, False)
        elif action == "undrain" and ok:
            discovery.set_draining(ep.url, False)
        return result

    return web.json_response(await _admin_fanout(targets, call))
