"""User-supplied request lifecycle hooks loaded from a Python file/module.

Capability parity with the reference's callbacks service
(``services/callbacks_service/callbacks.py:23-31``,
``custom_callbacks.py:20-55``): a module exposing ``pre_request`` (may
short-circuit with a response) and ``post_request`` (fire-and-forget).
"""

from __future__ import annotations

import importlib
import importlib.util
import sys
from typing import Any, Optional

from ...logging_utils import init_logger

logger = init_logger(__name__)


class CustomCallbackHandler:
    def __init__(self, module: Any):
        self.module = module
        self.pre_request = getattr(module, "pre_request", None)
        self.post_request = getattr(module, "post_request", None)

    async def call_pre_request(self, request, request_body: bytes, request_json: dict):
        """Returns a response-like object to short-circuit, or None."""
        if self.pre_request is None:
            return None
        result = self.pre_request(request, request_body, request_json)
        if hasattr(result, "__await__"):
            result = await result
        return result

    async def call_post_request(self, request, response_content: bytes):
        if self.post_request is None:
            return
        result = self.post_request(request, response_content)
        if hasattr(result, "__await__"):
            await result


# App-scoped (router.appscope): callbacks are per app, not per process.
_SCOPE_KEY = "custom_callback_handler"


def configure_custom_callbacks(spec: Optional[str]) -> Optional[CustomCallbackHandler]:
    """Load callbacks from ``path/to/file.py`` or ``dotted.module.name``."""
    from .. import appscope

    if not spec:
        appscope.scoped_set(_SCOPE_KEY, None)
        return None
    if spec.endswith(".py"):
        modspec = importlib.util.spec_from_file_location("pst_custom_callbacks", spec)
        module = importlib.util.module_from_spec(modspec)
        sys.modules["pst_custom_callbacks"] = module
        modspec.loader.exec_module(module)
    else:
        module = importlib.import_module(spec)
    handler = appscope.scoped_set(_SCOPE_KEY, CustomCallbackHandler(module))
    logger.info("loaded custom callbacks from %s", spec)
    return handler


def get_custom_callback_handler() -> Optional[CustomCallbackHandler]:
    from .. import appscope

    return appscope.scoped_get(_SCOPE_KEY)
