"""HTTP route table for the router (OpenAI surface + admin + metrics).

Capability parity with the reference's
``src/vllm_router/routers/main_router.py:40-231`` (route list in
SURVEY.md §1) and ``routers/metrics_router.py:57-123``.
"""

from __future__ import annotations

import time

import psutil
from aiohttp import web

from .. import __version__
from ..logging_utils import init_logger
from ..obs import (
    OBS_REGISTRY,
    debug_requests_response,
    error_headers,
    get_request_tracer,
    render_registries,
)
from ..resilience import get_admission_controller, get_breaker_registry
from ..resilience import metrics as res_gauges
from ..resilience.breaker import STATE_VALUE
from .service_discovery import get_service_discovery
from .state import GOSSIP_PATH, get_state_backend
from .services import fleet as fleet_service
from .services import metrics_service as gauges
from .services.request_service import (
    route_drain_request,
    route_general_request,
    route_sleep_wakeup_request,
)
from .stats.engine_stats import get_engine_stats_scraper
from .stats.request_stats import get_request_stats_monitor

logger = init_logger(__name__)

routes = web.RouteTableDef()


# ---------------------------------------------------------------------------
# OpenAI-compatible endpoints (proxied to engines)
# ---------------------------------------------------------------------------


@routes.post("/v1/chat/completions")
async def chat_completions(request: web.Request) -> web.StreamResponse:
    # Semantic-cache probe happens inside route_general_request (after the
    # body is parsed once); no pre-parse probe here.
    return await route_general_request(request, "/v1/chat/completions")


@routes.post("/v1/completions")
async def completions(request: web.Request) -> web.StreamResponse:
    return await route_general_request(request, "/v1/completions")


@routes.post("/v1/embeddings")
async def embeddings(request: web.Request) -> web.StreamResponse:
    return await route_general_request(request, "/v1/embeddings")


@routes.post("/v1/rerank")
@routes.post("/rerank")
async def rerank(request: web.Request) -> web.StreamResponse:
    return await route_general_request(request, "/v1/rerank")


@routes.post("/v1/score")
@routes.post("/score")
async def score(request: web.Request) -> web.StreamResponse:
    return await route_general_request(request, "/v1/score")


@routes.post("/tokenize")
async def tokenize(request: web.Request) -> web.StreamResponse:
    return await route_general_request(request, "/tokenize")


@routes.post("/detokenize")
async def detokenize(request: web.Request) -> web.StreamResponse:
    return await route_general_request(request, "/detokenize")


@routes.get("/v1/models")
async def list_models(request: web.Request) -> web.Response:
    """Aggregate model cards across all live engines (dedup by id)."""
    seen = {}
    for ep in get_service_discovery().get_endpoint_info():
        for model_id, info in ep.model_info.items():
            if model_id not in seen:
                seen[model_id] = {
                    "id": model_id,
                    "object": "model",
                    "created": info.created,
                    "owned_by": info.owned_by,
                    "parent": info.parent,
                    "root": info.root,
                }
        for model_id in ep.model_names:
            seen.setdefault(
                model_id,
                {
                    "id": model_id,
                    "object": "model",
                    "created": int(ep.added_timestamp),
                    "owned_by": "production-stack-tpu",
                    "parent": None,
                    "root": None,
                },
            )
    # Aliases appear as models so clients can discover them.
    aliases = getattr(get_service_discovery(), "aliases", None) or {}
    for alias, target in aliases.items():
        if alias not in seen and target in seen:
            card = dict(seen[target])
            card["id"] = alias
            seen[alias] = card
    return web.json_response({"object": "list", "data": list(seen.values())})


# ---------------------------------------------------------------------------
# Admin / observability
# ---------------------------------------------------------------------------


@routes.get("/version")
async def version(request: web.Request) -> web.Response:
    return web.json_response({"version": __version__})


@routes.get("/health")
async def health(request: web.Request) -> web.Response:
    """Composite health: discovery watcher + stats scraper must be live."""
    discovery = get_service_discovery()
    if not discovery.get_health():
        return web.json_response(
            {"status": "unhealthy", "reason": "service discovery watcher died"},
            status=503,
            headers=error_headers(request),
        )
    scraper = get_engine_stats_scraper()
    if not scraper.get_health():
        return web.json_response(
            {"status": "unhealthy", "reason": "engine stats scraper died"},
            status=503,
            headers=error_headers(request),
        )
    return web.json_response({"status": "healthy"})


@routes.get("/ready")
async def ready(request: web.Request) -> web.Response:
    """Readiness, distinct from liveness (the engine's warming≠unhealthy
    split, applied to the router): 503 while this replica's state view is
    not yet synced with its peers or while the replica is draining, so
    the load balancer withholds traffic without the pod being restarted.
    ``/health`` stays the liveness signal."""
    backend = get_state_backend()
    if request.app.get("router_draining"):
        return web.json_response(
            {"status": "draining", "reason": "draining"},
            status=503,
            headers=error_headers(
                request, extra={"X-PST-Router-Draining": "1"}
            ),
        )
    if backend is not None and not backend.synced():
        return web.json_response(
            {"status": "syncing", "reason": "state_sync",
             "state": backend.describe()},
            status=503,
            headers=error_headers(request),
        )
    payload = {"status": "ready"}
    if backend is not None:
        payload["state"] = backend.describe()
    return web.json_response(payload)


@routes.post("/router/drain")
async def router_drain(request: web.Request) -> web.Response:
    """Drain THIS router replica (rolling restarts): /ready flips 503 so
    the LB pulls it, new admission-path work is refused with
    ``X-PST-Router-Draining``, in-flight requests finish, and journal
    checkpoints are pushed to the surviving replicas immediately. The
    engine-fleet drain fan-out stays on ``POST /drain``."""
    request.app["router_draining"] = True
    backend = get_state_backend()
    if backend is not None:
        await backend.sync_now()
    return web.json_response({"status": "draining"})


@routes.post("/router/undrain")
async def router_undrain(request: web.Request) -> web.Response:
    request.app["router_draining"] = False
    return web.json_response({"status": "ok"})


@routes.post(GOSSIP_PATH)
async def state_gossip(request: web.Request) -> web.Response:
    """Replica-to-replica state-sync exchange (docs/router-ha.md): merge
    the caller's digest, answer with ours. 404 with the in-memory backend
    — a single replica has no peers and must not pretend otherwise."""
    # Resolve the app-scoped backend first so two in-process router apps
    # (multi-replica tests) exchange against their own state.
    backend = request.app.get("state_backend") or get_state_backend()
    if backend is None or not backend.shared:
        return web.json_response(
            {"error": {"message": "state replication is not enabled",
                       "type": "not_found_error", "code": 404}},
            status=404,
            headers=error_headers(request),
        )
    try:
        digest = await request.json()
    except ValueError:
        return web.json_response(
            {"error": {"message": "invalid digest", "code": 400,
                       "type": "invalid_request_error"}},
            status=400,
            headers=error_headers(request),
        )
    return web.json_response(backend.exchange(digest))


@routes.get("/engines")
async def engines(request: web.Request) -> web.Response:
    """Current engine pool with live engine- and request-level stats."""
    engine_stats = get_engine_stats_scraper().get_engine_stats()
    request_stats = get_request_stats_monitor().get_request_stats(time.time())
    registry = get_breaker_registry()
    out = []
    for ep in get_service_discovery().get_endpoint_info():
        es = engine_stats.get(ep.url)
        rs = request_stats.get(ep.url)
        out.append(
            {
                "url": ep.url,
                "id": ep.Id,
                "models": ep.model_names,
                "model_label": ep.model_label,
                "sleep": ep.sleep,
                "draining": ep.draining,
                "warming": ep.warming,
                "breaker": registry.state(ep.url).value if registry else None,
                "pod_name": ep.pod_name,
                "namespace": ep.namespace,
                "engine_stats": es.__dict__ if es else None,
                "request_stats": rs.__dict__ if rs else None,
            }
        )
    return web.json_response(out)


@routes.get("/metrics")
async def metrics(request: web.Request) -> web.Response:
    """Prometheus exposition: refresh gauges from live stats, then render.

    Parity: reference metrics_router.py:57-123 (also samples router-process
    CPU/mem/disk via psutil).
    """
    endpoints = get_service_discovery().get_endpoint_info()
    engine_stats = get_engine_stats_scraper().get_engine_stats()
    # LOCAL view only: each replica exports its own traffic; summing the
    # fleet-merged view across replicas would double-count in Prometheus.
    request_stats = get_request_stats_monitor().get_request_stats(
        time.time(), fleet=False
    )
    for ep in endpoints:
        url = ep.url
        es = engine_stats.get(url)
        if es is not None:
            gauges.gpu_prefix_cache_hit_rate.labels(server=url).set(
                es.gpu_prefix_cache_hit_rate
            )
            gauges.gpu_prefix_cache_hits_total.labels(server=url).set(
                es.gpu_prefix_cache_hits_total
            )
            gauges.gpu_prefix_cache_queries_total.labels(server=url).set(
                es.gpu_prefix_cache_queries_total
            )
            gauges.gpu_cache_usage_perc.labels(server=url).set(es.gpu_cache_usage_perc)
            gauges.num_requests_waiting.labels(server=url).set(es.num_queuing_requests)
        rs = request_stats.get(url)
        if rs is not None:
            gauges.current_qps.labels(server=url).set(rs.qps)
            gauges.avg_decoding_length.labels(server=url).set(rs.avg_decoding_length)
            gauges.num_prefill_requests.labels(server=url).set(rs.in_prefill_requests)
            gauges.num_decoding_requests.labels(server=url).set(rs.in_decoding_requests)
            gauges.num_requests_running.labels(server=url).set(
                rs.in_prefill_requests + rs.in_decoding_requests
            )
            gauges.avg_latency.labels(server=url).set(rs.avg_latency)
            gauges.avg_itl.labels(server=url).set(rs.avg_itl)
            gauges.num_requests_swapped.labels(server=url).set(rs.num_swapped_requests)
        gauges.healthy_pods_total.labels(server=url).set(1)
    # Resilience gauges: breaker states refresh here (covers engines whose
    # breaker transitioned while unscraped and half-open timers elapsing
    # between requests); queue depth + shed counters update at event sites.
    registry = get_breaker_registry()
    if registry is not None:
        for ep in endpoints:
            res_gauges.breaker_state.labels(server=ep.url).set(
                STATE_VALUE[registry.state(ep.url)]
            )
    controller = get_admission_controller()
    if controller is not None and controller.enabled:
        res_gauges.queue_depth.set(controller.queue_len())
    # Replication gauges: the gossip loop refreshes them every round; the
    # in-memory backend has no loop, so scrape time keeps them truthful
    # (1 replica, full admission share).
    backend = get_state_backend()
    if backend is not None:
        from .state import metrics as state_gauges

        state_gauges.replica_peers.set(backend.live_replica_count())
        state_gauges.admission_share.set(backend.admission_share())
    res_gauges.draining_engines.set(
        sum(1 for ep in endpoints if ep.draining)
    )
    res_gauges.warming_engines.set(
        sum(1 for ep in endpoints if ep.warming)
    )
    # Fleet phase counts (pst_fleet_engines): the scalar twin of the
    # /debug/fleet JSON, refreshed from this replica's discovery view.
    fleet_service.refresh_fleet_gauges(endpoints)
    # Capacity gauges (pst_capacity_*): recompute at scrape time so a
    # plain Prometheus pipeline sees live burn/saturation/hint without
    # anything polling /autoscale/signal.
    from .services.capacity import compute_signal, get_capacity_monitor

    cap_monitor = get_capacity_monitor()
    if cap_monitor is not None:
        compute_signal(cap_monitor, request.app)
    # Router-process resource usage.
    proc = psutil.Process()
    gauges.router_cpu_percent.set(proc.cpu_percent())
    gauges.router_memory_mb.set(proc.memory_info().rss / 1e6)
    gauges.router_disk_percent.set(psutil.disk_usage("/").percent)
    # Append the shared observability registry (pst_stage_duration_seconds)
    # — it lives outside the default registry (docs/observability.md).
    # A scraper negotiating OpenMetrics (Accept: application/
    # openmetrics-text) gets the exemplar-carrying exposition; everyone
    # else gets the plain text/plain body, byte-identical to before
    # exemplars existed.
    from prometheus_client import REGISTRY as _DEFAULT_REGISTRY

    accept = request.headers.get("Accept")
    body, content_type = render_registries(
        (_DEFAULT_REGISTRY, OBS_REGISTRY), accept
    )
    if content_type == "text/plain":
        return web.Response(body=body, content_type="text/plain")
    return web.Response(body=body, headers={"Content-Type": content_type})


@routes.get("/debug/requests")
async def debug_requests(request: web.Request) -> web.Response:
    """SDK-free trace debugging: the recorder's ring buffer of completed
    request timelines (one entry per request: trace id + per-stage spans
    with offsets/durations/attributes/events), most recent first.
    ``?limit=N`` bounds the reply; ``?request_id=`` filters to one request.
    """
    recorder = get_request_tracer()
    if recorder is None:
        return web.json_response(
            {"error": {"message": "request tracing is not initialized",
                       "type": "not_found_error", "code": 404}},
            status=404,
            headers=error_headers(request),
        )
    return debug_requests_response(recorder, request)


@routes.get("/autoscale/signal")
async def autoscale_signal(request: web.Request) -> web.Response:
    """Capacity signals (docs/observability.md "Capacity signals"): the
    autoscaler input — multi-window SLO burn rate, admission-queue depth
    + slope, gossip-merged fleet KV/compute headroom, and an absolute
    ``replica_hint``. Scrapeable by KEDA's metrics-api scaler today
    (docs/tutorials/21-keda-deep-dive.md); open like /metrics — it is
    aggregate telemetry, not per-request data."""
    from .services.capacity import compute_signal, get_capacity_monitor

    monitor = get_capacity_monitor()
    if monitor is None:
        return web.json_response(
            {"error": {"message": "capacity signals are disabled "
                                  "(--no-capacity-signal)",
                       "type": "not_found_error", "code": 404}},
            status=404,
            headers=error_headers(request),
        )
    return web.json_response(compute_signal(monitor, request.app))


@routes.get("/debug/fleet")
async def debug_fleet(request: web.Request) -> web.Response:
    """One gossip-merged snapshot of the whole deployment
    (docs/observability.md "Fleet debugging"): replica membership + sync
    ages, per-engine state (phase, breaker, routed in-flight fleet-wide,
    KV occupancy, canary TTFT, compile counters), the fleet-routing view
    and per-tenant DRR state. Served by every replica with identical
    content modulo one sync interval — ``pst-top`` renders it live."""
    return web.json_response(
        fleet_service.merged_fleet_snapshot(request.app)
    )


@routes.post("/sleep")
async def sleep(request: web.Request) -> web.Response:
    return await route_sleep_wakeup_request(request, "sleep")


@routes.post("/wake_up")
async def wake_up(request: web.Request) -> web.Response:
    return await route_sleep_wakeup_request(request, "wake_up")


@routes.get("/is_sleeping")
async def is_sleeping(request: web.Request) -> web.Response:
    return await route_sleep_wakeup_request(request, "is_sleeping")


@routes.post("/drain")
async def drain(request: web.Request) -> web.Response:
    """Fan graceful drain out to engines (by ?model= label or ?url=)."""
    return await route_drain_request(request, "drain")


@routes.post("/undrain")
async def undrain(request: web.Request) -> web.Response:
    return await route_drain_request(request, "undrain")


@routes.get("/is_draining")
async def is_draining(request: web.Request) -> web.Response:
    return await route_drain_request(request, "is_draining")
