"""Shared small utilities (URL validation, parsing helpers).

Capability parity with the reference router's ``src/vllm_router/utils.py``
(ModelType :49-81, url validation :84-102, ulimit bump :106-121,
alias/CSV parsing :124-147) — re-designed, not copied.

The reference's ``SingletonMeta`` lived here until the app-scope refactor
(docs/static-analysis.md, ``app-scope`` check): process-wide singletons
made two router apps in one process share state, so every former user
(routing policies, stats monitor/scraper, discovery) is now a plain class
resolved through :mod:`production_stack_tpu.router.appscope`.
"""

from __future__ import annotations

import enum
import ipaddress
import re
import resource
from typing import Dict, List, Optional


class ModelType(enum.Enum):
    """Model capability classes, each with a minimal health-probe payload.

    Mirrors the reference's ModelType (utils.py:49-81): the payload is a
    cheap request that exercises the corresponding endpoint.
    """

    chat = "/v1/chat/completions"
    completion = "/v1/completions"
    embeddings = "/v1/embeddings"
    rerank = "/v1/rerank"
    score = "/v1/score"

    @staticmethod
    def get_test_payload(model_type: str) -> dict:
        payloads = {
            "chat": {
                "messages": [{"role": "user", "content": "ping"}],
                "max_tokens": 1,
                "temperature": 0,
            },
            "completion": {"prompt": "ping", "max_tokens": 1, "temperature": 0},
            "embeddings": {"input": "ping"},
            "rerank": {"query": "ping", "documents": ["pong"]},
            "score": {"text_1": "ping", "text_2": "pong"},
        }
        return payloads[model_type]

    @staticmethod
    def get_all_fields() -> List[str]:
        return [m.name for m in ModelType]


_HOSTNAME_RE = re.compile(
    r"^(?=.{1,253}$)([a-zA-Z0-9](?:[a-zA-Z0-9-]{0,61}[a-zA-Z0-9])?\.)*"
    r"[a-zA-Z0-9](?:[a-zA-Z0-9-]{0,61}[a-zA-Z0-9])?$"
)


def validate_url(url: str) -> bool:
    """True iff url looks like http(s)://host[:port][/path] (IPv6 in brackets)."""
    m = re.match(r"^(https?)://(\[[0-9a-fA-F:]+\]|[^/:?#]+)(:\d{1,5})?([/?#].*)?$", url)
    if not m:
        return False
    host = m.group(2)
    if host.startswith("[") and host.endswith("]"):
        try:
            ipaddress.IPv6Address(host[1:-1])
            return True
        except ValueError:
            return False
    if m.group(3):
        port = int(m.group(3)[1:])
        if not (0 < port < 65536):
            return False
    try:
        ipaddress.ip_address(host)
        return True
    except ValueError:
        return bool(_HOSTNAME_RE.match(host))


def validate_static_urls(csv: str) -> bool:
    return all(validate_url(u) for u in parse_comma_separated(csv))


def parse_comma_separated(value: Optional[str]) -> List[str]:
    if not value:
        return []
    return [v.strip() for v in value.split(",") if v.strip()]


def parse_static_urls(value: str) -> List[str]:
    urls = parse_comma_separated(value)
    bad = [u for u in urls if not validate_url(u)]
    if bad:
        raise ValueError(f"invalid static backend url(s): {bad}")
    return urls


def parse_static_model_names(value: str) -> List[str]:
    return parse_comma_separated(value)


def parse_static_aliases(value: Optional[str]) -> Dict[str, str]:
    """Parse ``alias1:model1,alias2:model2`` into a dict."""
    aliases: Dict[str, str] = {}
    for pair in parse_comma_separated(value):
        if ":" not in pair:
            raise ValueError(f"bad alias spec {pair!r}, expected alias:model")
        alias, model = pair.split(":", 1)
        aliases[alias.strip()] = model.strip()
    return aliases


def set_ulimit(target_soft: int = 65535) -> None:
    """Raise RLIMIT_NOFILE soft limit for high-fanout proxying."""
    try:
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < target_soft:
            resource.setrlimit(
                resource.RLIMIT_NOFILE, (min(target_soft, hard), hard)
            )
    except (ValueError, OSError):
        pass


def update_content_length(headers: Dict[str, str], body: bytes) -> Dict[str, str]:
    """Return headers with Content-Length matching body (after rewrites)."""
    headers = {k: v for k, v in headers.items() if k.lower() != "content-length"}
    headers["Content-Length"] = str(len(body))
    return headers
