"""Per-backend circuit breakers, keyed by engine URL.

Classic three-state breaker (Nygard; the Envoy outlier-detection role in
the reference deployment):

- CLOSED: requests flow; ``failure_threshold`` consecutive failures trip
  the breaker OPEN.
- OPEN: the engine is not offered to routing. After ``recovery_time``
  seconds the breaker transitions to HALF_OPEN.
- HALF_OPEN: up to ``half_open_probes`` live requests are let through as
  probes. One success closes the breaker; one failure re-opens it (and
  restarts the recovery clock).

Fed from two directions: the proxy layer reports per-request outcomes
(connect errors / 5xx = failure, any streamed response = success) and the
service-discovery health loop reports probe outcomes. Both go through
``record_success`` / ``record_failure`` so the state machine has a single
writer surface. All methods are synchronous and loop-safe (no awaits, no
locks needed under asyncio's single-threaded execution).
"""

from __future__ import annotations

import contextlib
import enum
import time
from typing import Dict, List, Optional

from ..logging_utils import init_logger
from . import metrics

logger = init_logger(__name__)


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


# Gauge encoding for pst_resilience_breaker_state (dashboards map these).
STATE_VALUE = {
    BreakerState.CLOSED: 0,
    BreakerState.HALF_OPEN: 1,
    BreakerState.OPEN: 2,
}


class CircuitBreaker:
    def __init__(
        self,
        url: str,
        failure_threshold: int = 5,
        recovery_time: float = 10.0,
        half_open_probes: int = 1,
    ):
        self.url = url
        self.failure_threshold = max(1, failure_threshold)
        self.recovery_time = recovery_time
        self.half_open_probes = max(1, half_open_probes)
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self.opened_at: Optional[float] = None
        # HALF_OPEN probe reservations (timestamps). Entries expire after
        # recovery_time so an allows() answer that never became a request
        # (routing filtered this engine out) cannot wedge the breaker.
        # pstlint: owned-by=task:allows,_free_probe_slot,_transition
        self._probes: List[float] = []

    def _transition(self, state: BreakerState, now: float) -> None:
        if state is self.state:
            return
        logger.info(
            "breaker %s: %s -> %s", self.url, self.state.value, state.value
        )
        self.state = state
        metrics.breaker_transitions_total.labels(
            server=self.url, state=state.value
        ).inc()
        metrics.breaker_state.labels(server=self.url).set(STATE_VALUE[state])
        if state is BreakerState.OPEN:
            self.opened_at = now
            self._probes.clear()
        elif state is BreakerState.CLOSED:
            self.consecutive_failures = 0
            self.opened_at = None
            self._probes.clear()

    def _maybe_half_open(self, now: float) -> None:
        if (
            self.state is BreakerState.OPEN
            and self.opened_at is not None
            and now - self.opened_at >= self.recovery_time
        ):
            self._transition(BreakerState.HALF_OPEN, now)

    def current_state(self, now: Optional[float] = None) -> BreakerState:
        """Effective state (advances OPEN → HALF_OPEN when the recovery
        window has elapsed) WITHOUT reserving a probe slot — safe for
        observability readers."""
        self._maybe_half_open(now if now is not None else time.time())
        return self.state

    def _free_probe_slot(self, now: float) -> bool:
        ttl = max(self.recovery_time, 1.0)
        self._probes = [t for t in self._probes if now - t < ttl]
        return len(self._probes) < self.half_open_probes

    def would_allow(self, now: Optional[float] = None) -> bool:
        """State check WITHOUT reserving a probe slot — what routing uses
        to build the candidate list. Only ``allows()`` on the engine that
        routing actually picked consumes a slot."""
        now = now if now is not None else time.time()
        self._maybe_half_open(now)
        if self.state is BreakerState.CLOSED:
            return True
        if self.state is BreakerState.HALF_OPEN:
            return self._free_probe_slot(now)
        return False

    def allows(self, now: Optional[float] = None) -> bool:
        """May a request be sent to this engine right now?

        In HALF_OPEN, each ``allows() == True`` answer reserves one probe
        slot; the slot is released by the matching record_success/failure
        (and self-expires, so a reservation that never became a request
        cannot wedge the breaker).
        """
        now = now if now is not None else time.time()
        self._maybe_half_open(now)
        if self.state is BreakerState.CLOSED:
            return True
        if self.state is BreakerState.HALF_OPEN:
            if self._free_probe_slot(now):
                self._probes.append(now)
                return True
            return False
        return False

    def record_success(self, now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()
        self.consecutive_failures = 0
        if self.state is BreakerState.HALF_OPEN:
            self._transition(BreakerState.CLOSED, now)
        elif self.state is BreakerState.OPEN:
            # A success while OPEN (e.g. a health probe racing the trip):
            # the engine answered, so recover directly.
            self._transition(BreakerState.CLOSED, now)

    def record_failure(self, now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()
        self._maybe_half_open(now)
        if self.state is BreakerState.HALF_OPEN:
            self._transition(BreakerState.OPEN, now)
            return
        self.consecutive_failures += 1
        if (
            self.state is BreakerState.CLOSED
            and self.consecutive_failures >= self.failure_threshold
        ):
            self._transition(BreakerState.OPEN, now)


class CircuitBreakerRegistry:
    """One breaker per engine URL, created on first sighting."""

    def __init__(
        self,
        failure_threshold: int = 5,
        recovery_time: float = 10.0,
        half_open_probes: int = 1,
        state_backend=None,
    ):
        self.failure_threshold = failure_threshold
        self.recovery_time = recovery_time
        self.half_open_probes = half_open_probes
        # Replication (router HA): peers' breaker snapshots arrive via the
        # state backend; a breaker OPEN on any live replica blocks routing
        # here too, so a failing engine is fenced fleet-wide after one
        # replica's failure budget instead of once per replica.
        self.state_backend = state_backend
        # Single-writer surface: creation in get(), removal in evict()
        # — everything else only reads (or mutates breaker OBJECTS, whose
        # state machine is its own single surface via record_*).
        # pstlint: owned-by=task:get,evict
        self._breakers: Dict[str, CircuitBreaker] = {}

    def get(self, url: str) -> CircuitBreaker:
        b = self._breakers.get(url)
        if b is None:
            b = CircuitBreaker(
                url,
                failure_threshold=self.failure_threshold,
                recovery_time=self.recovery_time,
                half_open_probes=self.half_open_probes,
            )
            self._breakers[url] = b
            metrics.breaker_state.labels(server=url).set(
                STATE_VALUE[BreakerState.CLOSED]
            )
        return b

    def _remote_open(self, url: str) -> bool:
        """Whether any LIVE peer replica reports this engine's breaker
        OPEN (the state backend only surfaces live peers, so a dead
        replica's stale verdict cannot fence an engine forever)."""
        backend = self.state_backend
        if backend is None or not getattr(backend, "shared", False):
            return False
        return backend.remote_breaker_state(url) == "open"

    def allows(self, url: str, now: Optional[float] = None) -> bool:
        # Remote check first: a fleet-fenced engine must not consume a
        # half-open probe reservation it can never use.
        return not self._remote_open(url) and self.get(url).allows(now)

    def state(self, url: str) -> BreakerState:
        return self.get(url).current_state()

    def record_success(self, url: str, now: Optional[float] = None) -> None:
        self.get(url).record_success(now)

    def record_failure(self, url: str, now: Optional[float] = None) -> None:
        self.get(url).record_failure(now)

    def would_allow(self, url: str, now: Optional[float] = None) -> bool:
        return not self._remote_open(url) and self.get(url).would_allow(now)

    def filter_available(
        self, urls: List[str], now: Optional[float] = None
    ) -> List[str]:
        """URLs whose breakers admit traffic right now (side-effect-free).

        Fails open: if EVERY candidate's breaker refuses, return the full
        list — an all-dead fleet should surface real upstream errors (and
        give a recovered-but-not-yet-probed engine a chance), not turn the
        router into a permanent 503 wall.
        """
        allowed = [u for u in urls if self.would_allow(u, now)]
        return allowed or list(urls)

    def snapshot(self) -> Dict[str, str]:
        return {u: b.state.value for u, b in self._breakers.items()}

    def evict(self, url: str) -> None:
        """Drop the breaker and its per-server metric series for an engine
        that left the fleet (pod deleted / service removed). Without this,
        pod churn grows the registry and Prometheus label cardinality
        without bound."""
        if self._breakers.pop(url, None) is None:
            return
        with contextlib.suppress(KeyError):
            metrics.breaker_state.remove(url)
        for state in BreakerState:
            with contextlib.suppress(KeyError):
                metrics.breaker_transitions_total.remove(url, state.value)
        with contextlib.suppress(KeyError):
            metrics.retries_total.remove(url)
        with contextlib.suppress(KeyError):
            metrics.upstream_failures_total.remove(url)
