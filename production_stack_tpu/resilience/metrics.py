"""``pst_resilience_*`` Prometheus surface (default registry, like
:mod:`..router.services.metrics_service`).

Counters increment at event sites (breaker transitions, retries, sheds);
gauges are refreshed by the router's ``/metrics`` handler from live state.
"""

from prometheus_client import Counter, Gauge, Histogram

breaker_state = Gauge(
    "pst_resilience_breaker_state",
    "Circuit breaker state per engine (0=closed, 1=half-open, 2=open)",
    ["server"],
)
breaker_transitions_total = Counter(
    "pst_resilience_breaker_transitions_total",
    "Circuit breaker state transitions",
    ["server", "state"],
)
retries_total = Counter(
    "pst_resilience_retries_total",
    "Proxy attempts retried against the same or another engine",
    ["server"],
)
failovers_total = Counter(
    "pst_resilience_failovers_total",
    "Requests re-routed to a different engine after a failure",
)
upstream_failures_total = Counter(
    "pst_resilience_upstream_failures_total",
    "Upstream request failures observed by the proxy (connect error / 5xx)",
    ["server"],
)
admitted_total = Counter(
    "pst_resilience_admitted_total", "Requests admitted by admission control"
)
sheds_total = Counter(
    "pst_resilience_sheds_total",
    "Requests shed by admission control (429)",
    ["reason"],
)
queue_depth = Gauge(
    "pst_resilience_queue_depth", "Requests waiting in the admission queue"
)
client_disconnects_total = Counter(
    "pst_resilience_client_disconnects_total",
    "Client disconnects propagated as upstream aborts",
)
draining_engines = Gauge(
    "pst_resilience_draining_engines", "Engines currently draining"
)
warming_engines = Gauge(
    "pst_resilience_warming_engines",
    "Engines currently warming (startup precompile pass running; "
    "unroutable until /ready flips)",
)

# -- multi-tenant QoS (docs/multi-tenancy.md) -------------------------------

tenant_admitted_total = Counter(
    "pst_tenant_admitted_total",
    "Requests admitted through tenant-aware admission control, per tenant",
    ["tenant"],
)
tenant_sheds_total = Counter(
    "pst_tenant_sheds_total",
    "Requests shed by tenant-aware admission control, per tenant and "
    "reason (queue_full | deadline | timeout | expired)",
    ["tenant", "reason"],
)
tenant_queue_depth = Gauge(
    "pst_tenant_queue_depth",
    "Requests waiting in the weighted-fair admission queue, per tenant",
    ["tenant"],
)
tenant_usage_tokens_total = Counter(
    "pst_tenant_usage_tokens_total",
    "Metered tokens per tenant for billing, by direction (in = prompt "
    "tokens, out = completion tokens); exact when the upstream reported "
    "usage, body-size estimate otherwise",
    ["tenant", "direction"],
)

# -- deadlines & hedging (docs/resilience.md "Deadlines & hedging") ---------

deadline_budget_ms = Histogram(
    "pst_deadline_budget_ms",
    "Latency budget (ms) of deadline-carrying requests at router admission",
    buckets=(25, 50, 100, 200, 400, 800, 1600, 3200, 6400, 12800, 30000),
)
deadline_sheds_total = Counter(
    "pst_deadline_sheds_total",
    "Requests shed because their deadline budget was exhausted, by stage "
    "(router_admission | router_queue | router_retry | router_proxy)",
    ["stage"],
)
hedges_fired_total = Counter(
    "pst_hedge_fired_total", "Tail-latency hedge attempts issued"
)
hedges_won_total = Counter(
    "pst_hedge_won_total", "Hedge attempts whose response was served"
)
hedges_cancelled_total = Counter(
    "pst_hedge_cancelled_total",
    "Hedge attempts cancelled because the primary answered first",
)
hedges_suppressed_total = Counter(
    "pst_hedge_suppressed_total",
    "Hedge opportunities skipped, by reason "
    "(capacity | breaker | budget | no_candidate)",
    ["reason"],
)

# -- stream resumption (docs/resilience.md "Stream resumption") --------------

stream_resume_attempts_total = Counter(
    "pst_stream_resume_attempts_total",
    "Continuation legs issued after a mid-stream upstream death",
)
stream_resume_success_total = Counter(
    "pst_stream_resume_success_total",
    "Broken streams completed transparently (resumed on another engine "
    "or finished locally from the journal)",
)
stream_resume_failures_total = Counter(
    "pst_stream_resume_failures_total",
    "Broken streams where resume was attempted but the stream was still "
    "truncated (no candidate, legs exhausted, or budget too small)",
)
stream_truncated_total = Counter(
    "pst_stream_truncated_total",
    "Streams truncated mid-generation and terminated with a visible "
    "in-band error event, by reason "
    "(disabled | ineligible | engine_error | resume_failed)",
    ["reason"],
)
