"""``pst_resilience_*`` Prometheus surface (default registry, like
:mod:`..router.services.metrics_service`).

Counters increment at event sites (breaker transitions, retries, sheds);
gauges are refreshed by the router's ``/metrics`` handler from live state.
"""

from prometheus_client import Counter, Gauge

breaker_state = Gauge(
    "pst_resilience_breaker_state",
    "Circuit breaker state per engine (0=closed, 1=half-open, 2=open)",
    ["server"],
)
breaker_transitions_total = Counter(
    "pst_resilience_breaker_transitions_total",
    "Circuit breaker state transitions",
    ["server", "state"],
)
retries_total = Counter(
    "pst_resilience_retries_total",
    "Proxy attempts retried against the same or another engine",
    ["server"],
)
failovers_total = Counter(
    "pst_resilience_failovers_total",
    "Requests re-routed to a different engine after a failure",
)
upstream_failures_total = Counter(
    "pst_resilience_upstream_failures_total",
    "Upstream request failures observed by the proxy (connect error / 5xx)",
    ["server"],
)
admitted_total = Counter(
    "pst_resilience_admitted_total", "Requests admitted by admission control"
)
sheds_total = Counter(
    "pst_resilience_sheds_total",
    "Requests shed by admission control (429)",
    ["reason"],
)
queue_depth = Gauge(
    "pst_resilience_queue_depth", "Requests waiting in the admission queue"
)
client_disconnects_total = Counter(
    "pst_resilience_client_disconnects_total",
    "Client disconnects propagated as upstream aborts",
)
draining_engines = Gauge(
    "pst_resilience_draining_engines", "Engines currently draining"
)
