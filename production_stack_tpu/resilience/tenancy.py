"""Multi-tenant QoS: tenant identity, priority tiers, weighted fair sharing.

The north star is millions of users behind one fleet; without a tenant
dimension every overload decision (token bucket, admission queue, engine
ready queue, fleet scoring) is first-come-first-served, and one abusive
client starves everyone (ROADMAP item 1 — the reference stack's router
has no tenant concept at all, SURVEY §2). This module is the shared
vocabulary every layer speaks:

- **Identity** (:class:`TenantConfig`): a request's tenant is derived at
  router admission from its API key (strongest — the caller proved who
  they are) or the ``X-PST-Tenant`` header, falling back to the
  ``default`` tenant. The router then *stamps* ``X-PST-Tenant`` and
  ``X-PST-Tenant-Class`` on every upstream hop, so the engine scheduler
  and fleet scoring see the same identity admission derived — clients
  cannot self-assign a class.
- **Tiers**: ``interactive`` > ``batch``. Interactive work is latency
  SLO'd; batch work (the ``/v1/batches`` executor rides it) is
  throughput-oriented, preemptible, and never allowed to starve
  interactive prefills.
- **Weighted fairness** (:class:`WeightedFairQueue`): deficit round
  robin across tenants within a tier — each tenant's long-run service
  share is proportional to its weight, with the classic DRR O(1) bound
  (a tenant is never behind its ideal share by more than one quantum).
- **Metering**: per-tenant admitted/shed/usage counters
  (``pst_tenant_*``) for billing and the per-tenant SLO view.

Kept importable from both the router (asyncio admission) and the engine
(scheduler thread): no aiohttp, no prometheus at import time.
"""

from __future__ import annotations

import dataclasses
import json
from collections import OrderedDict, deque
from typing import Any, Deque, Dict, List, Mapping, Optional, Tuple

from ..logging_utils import init_logger

logger = init_logger(__name__)

# Priority tiers, best first. Everything unknown maps to interactive —
# failing "up" can only waste capacity on an abuser, while failing "down"
# would let a mislabeled interactive tenant be starved by design.
TIER_INTERACTIVE = "interactive"
TIER_BATCH = "batch"
TIERS = (TIER_INTERACTIVE, TIER_BATCH)

# Hop headers (stamped by the router at admission; the engine and fleet
# scoring trust them only because the router overwrites what clients
# sent — see app.py's admission middleware).
TENANT_HEADER = "X-PST-Tenant"
TENANT_CLASS_HEADER = "X-PST-Tenant-Class"

DEFAULT_TENANT = "default"

# Ad-hoc tenants (names seen on the wire with no configured spec) are
# tracked in bounded LRU tables: a flood of unique tenant names must cost
# O(cap), never O(traffic history).
MAX_ADHOC_TENANTS = 1024


def tier_rank(tier: Optional[str]) -> int:
    """Scheduling rank of a tier (lower = served first)."""
    return 1 if tier == TIER_BATCH else 0


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's QoS contract.

    ``rate``/``burst`` are absolute per-tenant admission limits (req/s);
    0 means "derive my share of the global rate from my weight". A
    ``deadline_ms`` > 0 assigns requests without an explicit
    ``X-PST-Deadline-Ms`` this default budget.
    """

    name: str
    weight: float = 1.0
    tier: str = TIER_INTERACTIVE
    rate: float = 0.0
    burst: int = 0
    deadline_ms: float = 0.0
    api_keys: Tuple[str, ...] = ()
    # True for ad-hoc (unconfigured) tenants: real for isolation (own
    # queue), but collapsed to one "other" metric label — Prometheus
    # label children are never evicted, so wire-controlled names must
    # not become label values.
    adhoc: bool = False

    @property
    def rank(self) -> int:
        return tier_rank(self.tier)

    @property
    def label(self) -> str:
        """The Prometheus label value for this tenant: configured names
        verbatim, the whole ad-hoc population as ``other`` (bounded
        cardinality whatever names the wire invents)."""
        return "other" if self.adhoc else self.name


def _coerce_spec(name: str, raw: Any) -> TenantSpec:
    if not isinstance(raw, dict):
        raw = {}
    tier = str(raw.get("tier") or TIER_INTERACTIVE)
    if tier not in TIERS:
        logger.warning(
            "tenant %r declares unknown tier %r; treating as interactive",
            name, tier,
        )
        tier = TIER_INTERACTIVE
    keys = raw.get("api_keys") or ()
    if isinstance(keys, str):
        keys = (keys,)
    return TenantSpec(
        name=name,
        weight=max(float(raw.get("weight") or 1.0), 1e-6),
        tier=tier,
        rate=max(float(raw.get("rate") or 0.0), 0.0),
        burst=max(int(raw.get("burst") or 0), 0),
        deadline_ms=max(float(raw.get("deadline_ms") or 0.0), 0.0),
        api_keys=tuple(str(k) for k in keys),
    )


class TenantConfig:
    """The tenant table: configured specs + ad-hoc defaults.

    Unknown tenant names resolve to an ad-hoc spec carrying the default
    weight/tier — they are real tenants for isolation purposes (own
    bucket, own queue) but share one default contract. The ad-hoc table
    is LRU-bounded so hostile unique names cannot grow router memory.
    """

    def __init__(
        self,
        tenants: Optional[Dict[str, TenantSpec]] = None,
        default_weight: float = 1.0,
        default_tier: str = TIER_INTERACTIVE,
        header: str = TENANT_HEADER,
    ) -> None:
        self.tenants: Dict[str, TenantSpec] = dict(tenants or {})
        self.default_weight = max(default_weight, 1e-6)
        self.default_tier = (
            default_tier if default_tier in TIERS else TIER_INTERACTIVE
        )
        self.header = header or TENANT_HEADER
        # pstlint: owned-by=task:__init__
        self._by_key: Dict[str, TenantSpec] = {}
        for spec in self.tenants.values():
            for key in spec.api_keys:
                self._by_key[key] = spec
        # pstlint: owned-by=task:resolve,spec_for
        self._adhoc: "OrderedDict[str, TenantSpec]" = OrderedDict()
        if DEFAULT_TENANT not in self.tenants:
            self.tenants[DEFAULT_TENANT] = TenantSpec(
                DEFAULT_TENANT,
                weight=self.default_weight,
                tier=self.default_tier,
            )

    @classmethod
    def from_file(
        cls,
        path: str,
        default_weight: float = 1.0,
        default_tier: str = TIER_INTERACTIVE,
        header: str = TENANT_HEADER,
    ) -> "TenantConfig":
        """Load ``{"tenants": {name: {weight, tier, rate, burst,
        deadline_ms, api_keys}}}`` from JSON or YAML."""
        with open(path) as f:
            text = f.read()
        if path.endswith((".yaml", ".yml")):
            import yaml

            data = yaml.safe_load(text)
        else:
            data = json.loads(text)
        if not isinstance(data, dict):
            raise ValueError(f"tenant config {path} must be a mapping")
        raw = data.get("tenants") or {}
        if not isinstance(raw, dict):
            raise ValueError(f"tenant config {path}: 'tenants' must map names to specs")
        tenants = {
            str(name): _coerce_spec(str(name), spec)
            for name, spec in raw.items()
        }
        return cls(
            tenants,
            default_weight=default_weight,
            default_tier=default_tier,
            header=header,
        )

    # -- identity ----------------------------------------------------------

    def spec_for(self, name: str) -> TenantSpec:
        spec = self.tenants.get(name)
        if spec is not None:
            return spec
        spec = self._adhoc.get(name)
        if spec is None:
            spec = TenantSpec(
                name, weight=self.default_weight, tier=self.default_tier,
                adhoc=True,
            )
            self._adhoc[name] = spec
            while len(self._adhoc) > MAX_ADHOC_TENANTS:
                self._adhoc.popitem(last=False)
        else:
            self._adhoc.move_to_end(name)
        return spec

    def resolve(
        self,
        headers: Mapping[str, str],
        api_key: Optional[str] = None,
    ) -> TenantSpec:
        """Tenant for one request: API key beats header beats default.

        The API key is authenticated identity; the header is client
        self-declaration, honored only when no key maps the caller to a
        configured tenant (useful behind a trusted gateway that already
        authenticated the caller and stamped the header). A configured
        tenant that declares ``api_keys`` can ONLY be claimed by one of
        them — a bare header naming it is an impersonation attempt and
        resolves to the default tenant instead of the protected
        contract (and instead of billing usage to the victim).
        """
        if api_key:
            spec = self._by_key.get(api_key)
            if spec is not None:
                return spec
        name = headers.get(self.header) or headers.get(self.header.lower())
        if name:
            stripped = str(name).strip()[:128]
            configured = self.tenants.get(stripped)
            if configured is not None and configured.api_keys:
                return self.tenants[DEFAULT_TENANT]
            return self.spec_for(stripped)
        return self.tenants[DEFAULT_TENANT]

    def weight_sum(self) -> float:
        """Total weight the global admission rate is shared across: every
        configured tenant plus one default-weight share standing in for
        the whole ad-hoc population (ad-hoc tenants split the default
        share rather than each minting a full one — otherwise inventing
        names would mint rate)."""
        return sum(s.weight for s in self.tenants.values())

    def describe(self) -> dict:
        return {
            "tenants": {
                name: {
                    "weight": s.weight, "tier": s.tier, "rate": s.rate,
                    "deadline_ms": s.deadline_ms,
                }
                for name, s in self.tenants.items()
            },
            "default_weight": self.default_weight,
            "default_tier": self.default_tier,
        }


class WeightedFairQueue:
    """Deficit round robin across (tier, tenant) with strict tier priority.

    Tiers are strictly ordered (every interactive waiter is considered
    before any batch waiter — the starvation direction the SLO cares
    about); *within* a tier tenants share by weight via DRR: each time a
    tenant's turn comes its deficit grows by ``quantum × weight``, it is
    served while the deficit covers the unit cost (1 per request), and
    the classic DRR bound holds — a backlogged tenant's service lags its
    ideal weighted share by at most one quantum's worth of requests.

    ``pop(ready)`` takes a predicate ("does this tenant have an admission
    token right now?") so per-tenant rate limiting composes: a tenant
    with waiters but no token is skipped without burning its deficit.
    """

    def __init__(self, quantum: float = 1.0) -> None:
        self.quantum = max(quantum, 1e-9)
        # Per tier: active tenant ring + per-tenant FIFO and deficit.
        # pstlint: owned-by=task:push,pop,discard,_retire
        self._queues: Dict[Tuple[int, str], Deque[Any]] = {}
        # pstlint: owned-by=task:push,pop,discard,_retire
        self._ring: Dict[int, Deque[str]] = {}
        # pstlint: owned-by=task:push,pop,discard,_retire
        self._deficit: Dict[Tuple[int, str], float] = {}

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def depth(self, tenant: str, rank: Optional[int] = None) -> int:
        if rank is not None:
            return len(self._queues.get((rank, tenant), ()))
        return sum(
            len(q) for (r, t), q in self._queues.items() if t == tenant
        )

    def has_waiters(self, tenant: str) -> bool:
        return self.depth(tenant) > 0

    def push(self, rank: int, tenant: str, item: Any) -> None:
        key = (rank, tenant)
        q = self._queues.get(key)
        if q is None:
            q = deque()
            self._queues[key] = q
            self._ring.setdefault(rank, deque()).append(tenant)
            self._deficit.setdefault(key, 0.0)
        q.append(item)

    def _retire(self, rank: int, tenant: str) -> None:
        """A tenant's queue drained: drop it from the ring and RESET its
        deficit — an idle tenant must not bank credit while idle (DRR's
        memoryless property; banking would let a tenant burst past its
        share after a quiet period)."""
        key = (rank, tenant)
        self._queues.pop(key, None)
        self._deficit.pop(key, None)
        ring = self._ring.get(rank)
        if ring is not None:
            try:
                ring.remove(tenant)
            except ValueError:
                pass
            if not ring:
                self._ring.pop(rank, None)

    def pop(self, ready=None, weight_of=None) -> Optional[Tuple[str, Any]]:
        """Serve one item: best tier first, DRR within the tier.

        ``ready(tenant)`` gates service (default: always ready);
        ``weight_of(tenant)`` supplies DRR weights (default 1.0).
        Returns ``(tenant, item)`` or None when nothing is servable.
        """
        for rank in sorted(self._ring):
            ring = self._ring[rank]
            # One full DRR cycle at most: every active tenant gets at
            # most one quantum top-up; if nobody is servable we stop
            # rather than growing deficits without bound.
            for _ in range(len(ring)):
                tenant = ring[0]
                key = (rank, tenant)
                q = self._queues.get(key)
                if not q:
                    self._retire(rank, tenant)
                    if not self._ring.get(rank):
                        break
                    continue
                if ready is not None and not ready(tenant):
                    # Skipped, credit retained: the fairness debt
                    # survives until the tenant can actually be served.
                    ring.rotate(-1)
                    continue
                # Classic DRR: top up only when depleted, then the
                # tenant stays at the front spending its deficit — a
                # weight-3 tenant serves 3 consecutive requests per
                # turn, a weight-1 tenant one.
                w = weight_of(tenant) if weight_of is not None else 1.0
                if self._deficit[key] < 1.0:
                    self._deficit[key] += self.quantum * max(w, 1e-6)
                if self._deficit[key] >= 1.0:
                    self._deficit[key] -= 1.0
                    item = q.popleft()
                    if not q:
                        self._retire(rank, tenant)
                    elif self._deficit[key] < 1.0:
                        ring.rotate(-1)  # quantum spent: next tenant
                    return tenant, item
                ring.rotate(-1)  # fractional weight: bank and move on
        return None

    def discard(self, predicate) -> int:
        """Drop items matching ``predicate(item)`` (timed-out waiters);
        returns how many were removed."""
        removed = 0
        for (rank, tenant), q in list(self._queues.items()):
            kept = deque(item for item in q if not predicate(item))
            removed += len(q) - len(kept)
            if kept:
                self._queues[(rank, tenant)] = kept
            else:
                self._retire(rank, tenant)
        return removed

    def tenants_waiting(self) -> List[Tuple[int, str]]:
        return [key for key, q in self._queues.items() if q]


class DeficitScheduler:
    """Engine-side DRR over tenant classes (no asyncio, no buckets): the
    scheduler's ready-queue ordering. One instance per engine scheduler;
    ``charge`` is called when a tenant's sequence is admitted, ``pick``
    chooses which of the currently waiting (tier, tenant) classes admits
    next. Weights arrive from the router via request headers — the engine
    trusts the stamped weight class, defaulting to 1.0.
    """

    def __init__(self, quantum: float = 1.0) -> None:
        self.quantum = max(quantum, 1e-9)
        # pstlint: owned-by=task:pick,charge
        self._credit: Dict[str, float] = {}

    # Credit clamp: the DRR lag bound. Without it a tenant charged while
    # running solo (no contested pick) would bank unbounded debt and be
    # starved for O(history) admissions when a competitor appears.
    CREDIT_BOUND = 4.0

    def pick(
        self, candidates: Dict[str, float]
    ) -> Optional[str]:
        """Choose among ``{tenant: weight}`` waiting classes: the tenant
        with the highest deficit-per-weight debt is served next; deficits
        grow by quantum × weight per pick so long-run admissions track
        weights. Single candidate short-circuits (the common case)."""
        if not candidates:
            return None
        if len(candidates) == 1:
            return next(iter(candidates))
        for t, w in candidates.items():
            self._credit[t] = min(
                self._credit.get(t, 0.0) + self.quantum * max(w, 1e-6),
                self.CREDIT_BOUND,
            )
        # Highest accumulated credit wins; ties break by name for
        # determinism (tests), which is fair over time because the loser
        # keeps its credit.
        best = max(
            candidates,
            key=lambda t: (self._credit.get(t, 0.0), t),
        )
        return best

    def charge(self, tenant: str) -> None:
        self._credit[tenant] = max(
            self._credit.get(tenant, 0.0) - 1.0, -self.CREDIT_BOUND
        )
        # Forget long-idle tenants opportunistically.
        if len(self._credit) > MAX_ADHOC_TENANTS:
            self._credit = {
                t: d for t, d in self._credit.items() if abs(d) > 1e-9
            }
