"""Transparent mid-stream failover: stream journaling + resumption.

The proxy's committed-stream rule (never retry after the first streamed
byte) is right for *replays* — a replay would duplicate already-delivered
tokens — but it turns every mid-stream engine death into a silently
truncated generation that looks complete to the client. This module gives
the router a third option: *continue* the generation on another engine.

The pieces, wired into ``proxy_and_stream``
(:mod:`..router.services.request_service`):

- :class:`SSEParser` — incremental ``data:`` frame reassembly. Upstream
  TCP chunks do not respect SSE frame boundaries, so the proxy forwards
  only *complete* events; a partial frame in flight when the engine dies
  is discarded instead of corrupting the client's framing.
- :class:`StreamJournal` — per-request accumulation of what the client
  has actually been sent: the chunk identity (``id``/``created``/
  ``model``), the concatenated delta text, a delta-chunk token count,
  ``finish_reason``/``usage``/``[DONE]``, and whether the engine reported
  an in-band error frame (engine-reported errors are deliberate — never
  resumed; only *transport* death is).
- :func:`build_continuation` — the resume request: original prompt +
  generated-so-far as the new prompt (chat: an appended assistant
  message), ``max_tokens`` reduced by tokens already delivered, ``echo``
  dropped and ``stream_options`` normalized so the continuation always
  reports usage the router can splice.
- continuation splicing (``feed_continuation``) — rewrites every
  continuation chunk to the original leg's ``id``/``created``/``model``,
  drops duplicate role-delta frames and any re-emitted overlap of
  already-delivered text, merges cross-leg ``usage`` so the client sees
  what one unbroken generation would have reported, and forwards exactly
  one ``data: [DONE]``.

Exclusions (fall back to visible truncation, never silent): ``n > 1`` /
``best_of > 1`` (choice indices would interleave across legs),
``logprobs`` (token offsets cannot be spliced), tool/function streaming
(partial tool-call arguments cannot be re-prompted), and ``echo``
(the continuation would re-echo the combined prompt).
"""

from __future__ import annotations

import json
import time
from typing import List, Optional

from ..logging_utils import init_logger

logger = init_logger(__name__)

DONE_FRAME = b"data: [DONE]\n\n"

_GENERATION_ENDPOINTS = ("/v1/completions", "/v1/chat/completions")


class StreamResumePolicy:
    """Router-level knobs for stream resumption (``--stream-resume``,
    ``--stream-resume-max-legs``)."""

    def __init__(self, enabled: bool = False, max_legs: int = 2):
        self.enabled = enabled
        self.max_legs = max(1, int(max_legs))


class SSEEvent:
    """One complete server-sent event. ``raw`` preserves the exact bytes
    received (frame delimiter included) so pass-through legs stay
    byte-identical to an unproxied stream."""

    __slots__ = ("raw", "data", "json", "is_done")

    def __init__(self, raw: bytes, data: Optional[str]):
        self.raw = raw
        self.data = data
        self.is_done = data is not None and data.strip() == "[DONE]"
        self.json: Optional[dict] = None
        if data is not None and not self.is_done:
            try:
                parsed = json.loads(data)
                if isinstance(parsed, dict):
                    self.json = parsed
            except ValueError:
                pass


class SSEParser:
    """Incremental SSE frame splitter: feed() arbitrary byte chunks, get
    complete events back; a trailing partial frame stays buffered."""

    def __init__(self):
        self._buf = b""

    def feed(self, chunk: bytes) -> List[SSEEvent]:
        self._buf += chunk
        events = []
        while True:
            # Spec-legal delimiters: blank line as LF-LF or CRLF-CRLF
            # (the byte sequences cannot overlap); take whichever comes
            # first so mixed upstreams still stream incrementally.
            i_lf = self._buf.find(b"\n\n")
            i_crlf = self._buf.find(b"\r\n\r\n")
            if i_crlf >= 0 and (i_lf < 0 or i_crlf < i_lf):
                idx, dlen = i_crlf, 4
            elif i_lf >= 0:
                idx, dlen = i_lf, 2
            else:
                break
            raw = self._buf[: idx + dlen]
            self._buf = self._buf[idx + dlen:]
            events.append(SSEEvent(raw, self._data_payload(raw)))
        return events

    def flush_raw(self) -> bytes:
        """Whatever partial frame is still buffered (forwarded verbatim on
        clean stream end, discarded on a mid-stream death)."""
        out, self._buf = self._buf, b""
        return out

    @staticmethod
    def _data_payload(raw: bytes) -> Optional[str]:
        parts = []
        for line in raw.split(b"\n"):
            line = line.rstrip(b"\r")
            if line.startswith(b"data:"):
                parts.append(line[5:].lstrip(b" ").decode("utf-8", "replace"))
        return "\n".join(parts) if parts else None


def resume_eligible(endpoint: str, request_json: Optional[dict]) -> bool:
    """Whether a broken stream of this request may be resumed. Sampling
    temperature does not matter (a continuation is a fresh sample of the
    *suffix*), but anything whose client-visible shape cannot be spliced
    across legs is excluded."""
    request_json = request_json or {}
    if endpoint not in _GENERATION_ENDPOINTS:
        return False
    if not request_json.get("stream"):
        return False
    try:
        if int(request_json.get("n") or 1) > 1:
            return False
        if int(request_json.get("best_of") or 1) > 1:
            return False
    except (TypeError, ValueError):
        return False
    if request_json.get("logprobs") or request_json.get("top_logprobs"):
        return False
    if request_json.get("echo"):
        return False
    for key in ("tools", "tool_choice", "functions", "function_call"):
        if request_json.get(key):
            return False
    if not isinstance(request_json.get("max_tokens"), int):
        # Without an explicit token budget the continuation leg would get
        # a fresh engine-default budget, so a resumed stream could run
        # (legs+1)× longer than any unbroken run.
        return False
    if endpoint == "/v1/chat/completions":
        if not isinstance(request_json.get("messages"), list):
            return False
        if request_json.get("continue_final_message"):
            # The client's own final assistant turn is already open; a
            # continuation would close it and open a second one, changing
            # the rendered context mid-generation.
            return False
    elif not isinstance(request_json.get("prompt", ""), str):
        # Batched prompt lists stream interleaved choice indices.
        return False
    return True


def build_continuation(
    request_json: dict, journal: "StreamJournal", endpoint: str
) -> dict:
    """The continuation request for the next leg: the generated-so-far
    text becomes part of the prompt, the token budget shrinks by what was
    already delivered, and the body is normalized so the new leg streams a
    usage the router can splice (``echo`` off, ``include_usage`` on —
    the journal strips the usage frame again if the client never asked)."""
    cont = dict(request_json)
    if endpoint == "/v1/chat/completions":
        messages = list(cont.get("messages") or [])
        if journal.text:
            messages.append({"role": "assistant", "content": journal.text})
            # The engine must render the final assistant turn OPEN and
            # continue it (no fresh generation prompt) — otherwise the
            # chat template would start a second, unrelated answer.
            cont["continue_final_message"] = True
        cont["messages"] = messages
    else:
        cont["prompt"] = str(cont.get("prompt", "")) + journal.text
    remaining = journal.remaining_tokens()
    if remaining is not None:
        cont["max_tokens"] = max(int(remaining), 1)
    cont["stream"] = True
    cont["stream_options"] = {"include_usage": True}
    cont.pop("echo", None)
    # A continuation is a fresh prefill on a different engine: any
    # disagg KV-transfer coordinates from the original leg are stale.
    cont.pop("kv_transfer_params", None)
    return cont


class StreamJournal:
    """What the client has been sent so far, plus the splicing state for
    continuation legs. One journal per committed stream."""

    def __init__(
        self,
        is_chat: bool,
        request_json: Optional[dict] = None,
        eligible: bool = False,
        record_text: bool = True,
    ):
        self.is_chat = is_chat
        self.request_json = request_json or {}
        self.eligible = eligible
        # Text is only needed to BUILD a continuation: when resume is off
        # or the request ineligible, skip accumulation so N concurrent
        # long streams never pile their full outputs up in router memory
        # (identity + token count still serve the visible-truncation tail).
        self.record_text = record_text
        self._parser = SSEParser()
        # Identity of the original leg, stamped onto continuation chunks.
        self.id: Optional[str] = None
        self.created: Optional[int] = None
        self.model: Optional[str] = None
        self.object: Optional[str] = None
        # Accounting. Text is kept as parts and joined lazily (once per
        # continuation leg) — per-chunk string concat would be O(n²) over
        # the stream length on the proxy hot path.
        # Resume-critical accumulation state. Single-writer surface:
        # only the journal's own frame machinery below may mutate the
        # annotated fields (enforced by the lock-discipline pstlint
        # check) — proxy code reads them and drives feed()/
        # start_continuation(); `legs` alone is proxy-written (see note).
        # pstlint: owned-by=task:_observe,_continuation_event,_flush_pending,_emit,start_continuation,synthesize_tail,truncation_tail,from_snapshot
        self._text_parts: List[str] = []
        # pstlint: owned-by=task:_observe,_continuation_event,_flush_pending,_emit,start_continuation,synthesize_tail,truncation_tail,from_snapshot
        self.delivered_tokens = 0  # content-bearing delta chunks ≈ tokens
        # pstlint: owned-by=task:_observe,_continuation_event,_flush_pending,_emit,start_continuation,synthesize_tail,truncation_tail,from_snapshot
        self.finish_reason: Optional[str] = None
        # pstlint: owned-by=task:_observe,_continuation_event,_flush_pending,_emit,start_continuation,synthesize_tail,truncation_tail,from_snapshot
        self.usage: Optional[dict] = None
        # pstlint: owned-by=task:_observe,_continuation_event,_flush_pending,_emit,start_continuation,synthesize_tail,truncation_tail
        self.saw_done = False
        # pstlint: owned-by=task:_observe,_continuation_event,_flush_pending,_emit,start_continuation,synthesize_tail,truncation_tail
        self.saw_error = False
        # pstlint: owned-by=task:_observe,_continuation_event,_flush_pending,_emit,start_continuation,synthesize_tail,truncation_tail,from_snapshot
        self.saw_role_delta = False
        # NOT annotated: legs is deliberately incremented by the proxy's
        # resume loop (request_service) when it launches a continuation —
        # a cross-module writer the same-file check cannot see.
        self.legs = 0  # continuation legs attempted
        # Delivered-token count at the last replicated checkpoint (None =
        # never checkpointed); maintained by the proxy's checkpoint helper
        # (request_service), same cross-module-writer note as ``legs``.
        self.checkpointed_tokens: Optional[int] = None
        # Per-continuation-leg splice state.
        # pstlint: owned-by=task:_observe,_continuation_event,_flush_pending,_emit,start_continuation,synthesize_tail,truncation_tail
        self._overlap = ""
        # pstlint: owned-by=task:_observe,_continuation_event,_flush_pending,_emit,start_continuation,synthesize_tail,truncation_tail
        self._pending: List[tuple] = []  # held-back possible-echo frames
        # pstlint: owned-by=task:_observe,_continuation_event,_flush_pending,_emit,start_continuation,synthesize_tail,truncation_tail
        self._tokens_at_leg_start = 0

    @property
    def text(self) -> str:
        return "".join(self._text_parts)

    # -- replica takeover (docs/router-ha.md) --------------------------------

    def to_snapshot(self) -> dict:
        """The JSON-safe checkpoint a router replica gossips to peers so a
        survivor can resume this stream after the owner dies: original-leg
        identity, delivered text/token count, and the continuation budget.
        Per-leg splice state is deliberately excluded — a takeover always
        begins a fresh continuation leg via ``start_continuation``."""
        return {
            "is_chat": self.is_chat,
            "request_json": self.request_json,
            "id": self.id,
            "created": self.created,
            "model": self.model,
            "object": self.object,
            "text": self.text,
            "delivered_tokens": self.delivered_tokens,
            "finish_reason": self.finish_reason,
            "usage": self.usage,
            "legs": self.legs,
            "saw_role_delta": self.saw_role_delta,
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "StreamJournal":
        """Rebuild a journal from a peer's checkpoint on the surviving
        replica. The result is resume-ready (eligible, text recorded): the
        survivor issues continuation legs exactly as the owner would have."""
        journal = cls(
            bool(snap.get("is_chat")),
            request_json=snap.get("request_json") or {},
            eligible=True,
            record_text=True,
        )
        journal.id = snap.get("id")
        journal.created = snap.get("created")
        journal.model = snap.get("model")
        journal.object = snap.get("object")
        text = snap.get("text") or ""
        if text:
            journal._text_parts = [text]
        journal.delivered_tokens = int(snap.get("delivered_tokens") or 0)
        journal.finish_reason = snap.get("finish_reason")
        journal.usage = snap.get("usage")
        journal.legs = int(snap.get("legs") or 0)
        journal.saw_role_delta = bool(snap.get("saw_role_delta"))
        return journal

    # -- eligibility / budget ----------------------------------------------

    def resumable(self) -> bool:
        """Whether a *resume* may be attempted for this broken stream: the
        request shape must be spliceable, the stream must not have ended
        ([DONE]), and the engine must not have reported an in-band error
        (a deliberate rejection — replaying it elsewhere would retry work
        the engine refused on purpose)."""
        return self.eligible and not self.saw_done and not self.saw_error

    def remaining_tokens(self) -> Optional[int]:
        max_tokens = self.request_json.get("max_tokens")
        if isinstance(max_tokens, int):
            return max_tokens - self.delivered_tokens
        return None

    def client_wants_usage(self) -> bool:
        opts = self.request_json.get("stream_options") or {}
        return bool(isinstance(opts, dict) and opts.get("include_usage"))

    # -- leg 1: pass-through with observation --------------------------------

    def feed(self, chunk: bytes) -> bytes:
        """Leg-1 path: observe every complete event and return its exact
        original bytes for forwarding (byte-identical pass-through)."""
        out = []
        for ev in self._parser.feed(chunk):
            self._observe(ev)
            out.append(ev.raw)
        return b"".join(out)

    def flush_raw(self) -> bytes:
        return self._parser.flush_raw()

    def _observe(self, ev: SSEEvent) -> None:
        if ev.is_done:
            self.saw_done = True
            return
        obj = ev.json
        if obj is None:
            return
        if "error" in obj:
            self.saw_error = True
            return
        if self.id is None and obj.get("id"):
            self.id = obj.get("id")
            self.created = obj.get("created")
            self.model = obj.get("model")
            self.object = obj.get("object")
        delta_text, finish, delta = self._choice_fields(obj)
        if delta and "role" in delta:
            self.saw_role_delta = True
        if delta_text:
            if self.record_text:
                self._text_parts.append(delta_text)
            self.delivered_tokens += 1
        if finish:
            self.finish_reason = finish
        if obj.get("usage"):
            self.usage = obj["usage"]

    def _choice_fields(self, obj: dict):
        """(delta_text, finish_reason, chat_delta) of choice 0."""
        choices = obj.get("choices") or []
        if not choices or not isinstance(choices[0], dict):
            return None, None, None
        choice = choices[0]
        if self.is_chat:
            delta = choice.get("delta") or {}
            return delta.get("content"), choice.get("finish_reason"), delta
        return choice.get("text"), choice.get("finish_reason"), None

    # -- continuation legs: rewrite + splice ---------------------------------

    def start_continuation(self) -> None:
        """Reset per-leg splice state for a fresh upstream SSE stream."""
        self._parser = SSEParser()
        self._overlap = self.text
        self._pending = []
        self._tokens_at_leg_start = self.delivered_tokens

    def feed_continuation(self, chunk: bytes) -> bytes:
        out = []
        for ev in self._parser.feed(chunk):
            rewritten = self._continuation_event(ev)
            if rewritten:
                out.append(rewritten)
        return b"".join(out)

    def _continuation_event(self, ev: SSEEvent) -> Optional[bytes]:
        if ev.is_done:
            out = self._flush_pending()
            if self.saw_done:
                return out or None
            self.saw_done = True
            return out + DONE_FRAME
        obj = ev.json
        if obj is None:
            return self._flush_pending() + ev.raw
        if "error" in obj:
            # Engine-reported error on the continuation leg: forward it
            # (visible, never silently dropped) and stop resuming.
            self.saw_error = True
            return self._flush_pending() + ev.raw
        delta_text, finish, delta = self._choice_fields(obj)
        # Re-emitted prefix (an engine that echoes despite the normalized
        # continuation): deltas matching the delivered text are HELD BACK,
        # not dropped — only a replay of the entire prefix is discarded as
        # an echo. The moment the leg diverges, the held-back frames were
        # legitimate suffix tokens (the generation merely re-sampled the
        # same opening words) and are flushed to the client intact.
        if delta_text and self._overlap:
            if self._overlap.startswith(delta_text):
                self._pending.append((obj, delta_text, finish, delta))
                self._overlap = self._overlap[len(delta_text):]
                if not self._overlap:
                    # Full-prefix re-emission confirmed: an echo — drop it.
                    self._pending = []
                return None
            if delta_text.startswith(self._overlap):
                # The delta spans the END of the echoed prefix (fresh
                # legs chunk differently): held-back frames + this
                # delta's head reproduce the full delivered text — echo
                # confirmed. Drop the echo, forward only the new suffix.
                suffix = delta_text[len(self._overlap):]
                self._pending = []
                self._overlap = ""
                obj = self._replace_delta_text(obj, suffix)
                _, finish, delta = self._choice_fields(obj)
                return self._emit(obj, suffix, finish, delta)
            return self._flush_pending() + (
                self._emit(obj, delta_text, finish, delta) or b""
            ) or None
        if self._pending:
            # Non-delta frame (finish/usage/role) ends the overlap window.
            return self._flush_pending() + (
                self._emit(obj, delta_text, finish, delta) or b""
            ) or None
        return self._emit(obj, delta_text, finish, delta)

    def _replace_delta_text(self, obj: dict, new_text: str) -> dict:
        obj = dict(obj)
        choices = [dict(c) for c in (obj.get("choices") or [])]
        if choices:
            if self.is_chat:
                delta = dict(choices[0].get("delta") or {})
                delta["content"] = new_text
                choices[0]["delta"] = delta
            else:
                choices[0]["text"] = new_text
        obj["choices"] = choices
        return obj

    def _flush_pending(self) -> bytes:
        """The leg diverged (or ended) before re-emitting the whole
        delivered prefix: the held-back deltas were real output."""
        pending, self._pending = self._pending, []
        self._overlap = ""
        out = b""
        for obj, delta_text, finish, delta in pending:
            out += self._emit(obj, delta_text, finish, delta) or b""
        return out

    def _emit(self, obj, delta_text, finish, delta) -> Optional[bytes]:
        """Rewrite one continuation frame to the original leg's identity
        and account for it. Returns None for frames with nothing left to
        forward."""
        # Duplicate role-announcement frame (chat legs each open with one).
        if (
            self.is_chat
            and delta is not None
            and "role" in delta
            and not delta.get("content")
            and not finish
            and not obj.get("usage")
            and self.saw_role_delta
        ):
            return None
        obj = dict(obj)
        if self.id is not None:
            obj["id"] = self.id
        if self.created is not None:
            obj["created"] = self.created
        if self.model is not None:
            obj["model"] = self.model
        if obj.get("usage"):
            merged = self._merge_usage(obj["usage"])
            self.usage = merged
            if self.client_wants_usage():
                obj["usage"] = merged
            else:
                # The continuation forced include_usage for the router's
                # own accounting; the client never asked for it.
                obj.pop("usage", None)
                if not obj.get("choices"):
                    return None  # usage-only frame: nothing left to send
        if delta is not None and "role" in delta:
            self.saw_role_delta = True
        if delta_text:
            self._text_parts.append(delta_text)
            self.delivered_tokens += 1
        if finish:
            self.finish_reason = finish
        return f"data: {json.dumps(obj)}\n\n".encode()

    def _merge_usage(self, leg_usage: dict) -> dict:
        """Client-visible usage of one unbroken generation: completion
        tokens accumulate across legs; the continuation's prompt includes
        the delivered prefix, so subtracting it recovers the original
        prompt size."""
        prev = self._tokens_at_leg_start
        completion = int(leg_usage.get("completion_tokens") or 0) + prev
        prompt = max(int(leg_usage.get("prompt_tokens") or 0) - prev, 0)
        return {
            "prompt_tokens": prompt,
            "completion_tokens": completion,
            "total_tokens": prompt + completion,
        }

    # -- terminal frames -----------------------------------------------------

    def _closing_chunk(self, finish_reason: str) -> bytes:
        if self.is_chat:
            choice = {"index": 0, "delta": {}, "finish_reason": finish_reason}
            obj_type = self.object or "chat.completion.chunk"
        else:
            choice = {"index": 0, "text": "", "finish_reason": finish_reason}
            obj_type = self.object or "text_completion"
        obj = {
            "id": self.id or "",
            "object": obj_type,
            "created": self.created if self.created is not None else int(time.time()),
            "model": self.model or self.request_json.get("model", ""),
            "choices": [choice],
        }
        return f"data: {json.dumps(obj)}\n\n".encode()

    def synthesize_tail(self) -> bytes:
        """Locally finish a stream whose generation is already complete
        (the engine died *after* the last token but before the terminal
        framing): a closing ``finish_reason`` chunk if none was delivered,
        then the single ``[DONE]``. No continuation leg needed.

        Known limit: engines in this stack embed ``usage`` in the final
        finish-bearing delta, so a delivered generation has its usage. An
        engine that ships usage as a *separate* trailing frame and dies
        exactly between finish and usage leaves an ``include_usage``
        client without one — the router cannot tokenize the prompt to
        reconstruct it."""
        out = b""
        if self.finish_reason is None and not self.saw_error:
            out += self._closing_chunk("length")
            self.finish_reason = "length"
        if not self.saw_done:
            out += DONE_FRAME
            self.saw_done = True
        return out

    def truncation_tail(
        self, message: str = "upstream engine failed mid-stream; "
                             "response truncated"
    ) -> bytes:
        """Visible truncation: a terminal in-band error event plus
        ``[DONE]`` so clients can tell a broken generation from a complete
        one (an engine-reported error frame already on the wire is not
        duplicated)."""
        out = b""
        if not self.saw_error and not self.saw_done:
            err = {
                "error": {
                    "message": message,
                    "type": "upstream_error",
                    "code": "stream_truncated",
                }
            }
            out += f"data: {json.dumps(err)}\n\n".encode()
        if not self.saw_done:
            out += DONE_FRAME
            self.saw_done = True
        return out
