"""Admission control: token-bucket rate limiting + bounded priority queue
with deadline-based load shedding.

Sits ahead of ``route_general_request`` (wired as an aiohttp middleware in
the router app). Semantics:

- ``rate`` requests/second refill a bucket of ``burst`` capacity. A request
  that finds a token is admitted immediately.
- Without a token, the request waits in a bounded priority queue (priority
  from the ``X-Request-Priority`` header, higher served first; FIFO within
  a priority level). A dispatcher task grants tokens to waiters as they
  refill.
- Shedding is deadline-based: a request is rejected with 429 +
  ``Retry-After`` when the queue is full, when the bucket cannot possibly
  produce its token within ``queue_timeout`` (no point parking it), or
  when its wait actually exceeds ``queue_timeout``.
- Requests carrying an end-to-end budget (``X-PST-Deadline-Ms``,
  :mod:`.deadline`) additionally cap their queue wait at the remaining
  budget, and the *dequeue* re-checks the budget against ``min_budget``
  (the proxy's connect-timeout floor): a request granted its token just
  under the wire with ~0 budget left is doomed work and is shed with the
  ``expired`` reason (mapped to 504 upstream) instead of being forwarded.

``rate <= 0`` disables rate limiting entirely (every request admitted).
"""

from __future__ import annotations

import asyncio
import heapq
import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..logging_utils import init_logger
from ..obs.tasks import spawn_owned
from . import metrics
from .deadline import Deadline
from .tenancy import DEFAULT_TENANT, TenantConfig, TenantSpec, WeightedFairQueue

logger = init_logger(__name__)


class TokenBucket:
    def __init__(self, rate: float, burst: int):
        self.rate = rate
        self.capacity = max(1, burst)
        self.tokens = float(self.capacity)
        # Anchored on first use so callers may drive the bucket on any
        # monotonic timebase (tests pass synthetic timestamps). Defaults
        # ride time.monotonic(): an NTP step must neither freeze refill
        # nor grant a burst for free.
        self.last_refill: Optional[float] = None

    def _refill(self, now: float) -> None:
        if self.last_refill is None:
            self.last_refill = now
        if now > self.last_refill:
            self.tokens = min(
                self.capacity, self.tokens + (now - self.last_refill) * self.rate
            )
            self.last_refill = now

    def try_acquire(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.monotonic()
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def time_until_tokens(self, n: float, now: Optional[float] = None) -> float:
        """Seconds until ``n`` tokens are available (0 if already there)."""
        now = now if now is not None else time.monotonic()
        self._refill(now)
        if self.tokens >= n:
            return 0.0
        return (n - self.tokens) / self.rate


@dataclass
class AdmissionDecision:
    admitted: bool
    reason: str = ""  # queue_full | deadline | timeout | expired
    retry_after: float = 0.0

    @property
    def retry_after_header(self) -> str:
        return str(max(1, math.ceil(self.retry_after)))


_ADMIT = AdmissionDecision(admitted=True)


@dataclass(order=True)
class _Waiter:
    sort_key: Tuple[float, int]
    future: asyncio.Future = field(compare=False)


class AdmissionController:
    def __init__(
        self,
        rate: float = 0.0,
        burst: int = 0,
        max_queue: int = 128,
        queue_timeout: float = 5.0,
        state_backend=None,
        tenants: Optional[TenantConfig] = None,
    ):
        # ``rate``/``burst`` are FLEET-WIDE limits. With a shared state
        # backend each replica admits only its membership share
        # (rate/n live replicas — rate splitting), so N replicas enforce
        # the same global limit one replica would, and a replica death
        # shifts — never multiplies — the fleet's effective limit: the
        # survivors' shares grow only when the dead peer ages out of the
        # membership view. Without a backend the share is 1.0 and the
        # controller behaves exactly as before.
        self.rate = rate
        self.enabled = rate > 0
        self.max_queue = max(0, max_queue)
        self.queue_timeout = queue_timeout
        self.state_backend = state_backend
        self._capacity = float(max(1, burst or math.ceil(rate))) if rate > 0 else 1.0
        self._share = 1.0
        self.bucket = TokenBucket(rate, burst or math.ceil(rate)) if self.enabled else None
        # pstlint: owned-by=task:admit,_dispatch_loop,close
        self._heap: List[_Waiter] = []
        self._seq = 0
        self._dispatcher: Optional[asyncio.Task] = None
        self._wakeup: Optional[asyncio.Event] = None
        # Multi-tenant mode (docs/multi-tenancy.md): the single shared
        # bucket becomes per-tenant weighted buckets (each tenant's
        # guaranteed refill is its weight share of the global rate, or
        # its explicit absolute rate), and the priority heap becomes a
        # weighted-fair (deficit round robin) queue with strict tier
        # priority. With ``tenants=None`` nothing below exists and the
        # controller behaves exactly as before.
        self.tenants = tenants
        # pstlint: owned-by=task:tenant_bucket,_apply_share
        self._tenant_buckets: Dict[str, TokenBucket] = {}
        self._wfq = WeightedFairQueue() if tenants is not None else None
        # Per-tenant admitted/shed totals (keyed by the BOUNDED metric
        # label — ad-hoc names collapse to "other"), read back by the
        # fleet-introspection snapshot (GET /debug/fleet "tenants" view).
        # pstlint: owned-by=task:admit,_admit_tenant,_shed
        self._tenant_admitted: Dict[str, int] = {}
        # pstlint: owned-by=task:_shed
        self._tenant_sheds: Dict[str, int] = {}

    def _apply_share(self) -> None:
        """Pull the current membership share and rescale the local bucket
        (rate AND burst capacity — a replica death must not leave the
        fleet with 2× the configured burst). Tenant buckets rescale the
        same way: each tenant's *fleet-wide* guarantee splits across live
        replicas, so two gossiping replicas together enforce exactly the
        per-tenant limits one replica would."""
        backend = self.state_backend
        if backend is None or not getattr(backend, "shared", False):
            return
        share = backend.admission_share()
        if share == self._share or self.bucket is None:
            return
        self._share = share
        self.bucket.rate = max(self.rate * share, 1e-9)
        new_capacity = max(self._capacity * share, 1.0)
        self.bucket.tokens = min(self.bucket.tokens, new_capacity)
        self.bucket.capacity = new_capacity
        for b in self._tenant_buckets.values():
            self._rescale_bucket(b)

    def _rescale_bucket(self, b: TokenBucket) -> None:
        b.rate = max(b.base_rate * self._share, 1e-9)
        cap = max(b.base_capacity * self._share, 1.0)
        b.tokens = min(b.tokens, cap)
        b.capacity = cap

    def tenant_bucket(self, spec: TenantSpec) -> TokenBucket:
        """The tenant's own refill bucket: its explicit absolute rate, or
        its weight share of the global rate. Created lazily; bounded (an
        ad-hoc tenant flood must cost O(cap) buckets, not O(names)).

        AD-HOC tenants (names with no configured spec) all draw from the
        DEFAULT tenant's bucket: the whole ad-hoc population shares one
        default-weight slice of the global rate — otherwise rotating
        invented names would mint a fresh full share per name and bypass
        ``--admission-rate`` entirely. They still queue per name (DRR
        fairness among them), but tokens come from the shared slice."""
        if spec.name not in self.tenants.tenants:
            spec = self.tenants.tenants[DEFAULT_TENANT]
        b = self._tenant_buckets.get(spec.name)
        if b is None:
            rate = spec.rate
            if rate <= 0:
                rate = self.rate * spec.weight / max(
                    self.tenants.weight_sum(), 1e-9
                )
            rate = max(rate, 1e-9)
            burst = spec.burst or max(math.ceil(rate), 1)
            b = TokenBucket(rate, burst)
            b.base_rate = rate
            b.base_capacity = float(b.capacity)
            if self._share != 1.0:
                self._rescale_bucket(b)
            if len(self._tenant_buckets) >= 4096:
                # Evict an idle (full) ad-hoc bucket; a full bucket holds
                # no state worth keeping (recreation is identical).
                for name, old in list(self._tenant_buckets.items()):
                    if (
                        name not in self.tenants.tenants
                        and old.tokens >= old.capacity
                    ):
                        del self._tenant_buckets[name]
                        break
            self._tenant_buckets[spec.name] = b
        return b

    # -- internals --------------------------------------------------------

    def queue_len(self) -> int:
        n = sum(1 for w in self._heap if not w.future.done())
        if self._wfq is not None:
            n += len(self._wfq)
        return n

    def _waiters_ahead(self, priority: int) -> int:
        """Waiters the dispatcher would serve before a new request at
        ``priority``: strictly higher priorities, plus equal priorities
        already queued (FIFO within a level)."""
        return sum(
            1
            for w in self._heap
            if not w.future.done() and w.sort_key[0] <= -priority
        )

    def _ensure_dispatcher(self) -> None:
        if self._dispatcher is None or self._dispatcher.done():
            self._wakeup = asyncio.Event()
            loop = (
                self._dispatch_tenants()
                if self._wfq is not None
                else self._dispatch_loop()
            )
            self._dispatcher = spawn_owned(loop, name="admission-dispatcher")

    async def _dispatch_loop(self) -> None:
        """Grant refilled tokens to waiters, highest priority first."""
        while True:
            while not self._heap:
                self._wakeup.clear()
                await self._wakeup.wait()
            delay = self.bucket.time_until_tokens(1.0)
            if delay > 0:
                await asyncio.sleep(delay)
            while self._heap and self._heap[0].future.done():
                heapq.heappop(self._heap)  # timed out / cancelled waiters
            if not self._heap:
                continue
            if self.bucket.try_acquire():
                waiter = heapq.heappop(self._heap)
                if not waiter.future.done():  # may have timed out just now
                    waiter.future.set_result(True)
                metrics.queue_depth.set(self.queue_len())

    async def _dispatch_tenants(self) -> None:
        """Tenant-mode dispatcher: grant each waiting tenant's own tokens
        as they refill, serving tiers strictly (interactive first) and
        tenants within a tier by deficit round robin. A tenant whose
        bucket is dry is skipped without burning its DRR deficit — its
        fairness debt survives until it can actually be served."""

        def _ready(name: str) -> bool:
            spec = self.tenants.spec_for(name)
            return self.tenant_bucket(spec).time_until_tokens(1.0) <= 0.0

        def _weight(name: str) -> float:
            return self.tenants.spec_for(name).weight

        while True:
            while not len(self._wfq):
                self._wakeup.clear()
                await self._wakeup.wait()
            self._wfq.discard(lambda f: f.done())  # timed-out waiters
            if not len(self._wfq):
                continue
            # Serve everything currently servable (pop returns None when
            # every waiting tenant's bucket is dry).
            served = False
            while True:
                got = self._wfq.pop(ready=_ready, weight_of=_weight)
                if got is None:
                    break
                name, fut = got
                spec = self.tenants.spec_for(name)
                self.tenant_bucket(spec).try_acquire()
                if not fut.done():
                    fut.set_result(True)
                served = True
                metrics.tenant_queue_depth.labels(tenant=spec.label).set(
                    self._wfq.depth(name)
                )
            metrics.queue_depth.set(self.queue_len())
            if served and len(self._wfq):
                continue
            # Sleep until the soonest waiting tenant can have a token —
            # interruptibly, so a new arrival whose tenant already has
            # tokens is granted immediately instead of waiting out a slow
            # tenant's refill.
            waiting = self._wfq.tenants_waiting()
            if not waiting:
                continue
            delay = min(
                self.tenant_bucket(
                    self.tenants.spec_for(name)
                ).time_until_tokens(1.0)
                for _, name in waiting
            )
            if delay > 0:
                self._wakeup.clear()
                try:
                    await asyncio.wait_for(
                        self._wakeup.wait(), timeout=delay
                    )
                except asyncio.TimeoutError:
                    pass

    # -- public API -------------------------------------------------------

    async def admit(
        self,
        priority: int = 0,
        deadline: Optional[Deadline] = None,
        min_budget: float = 0.0,
        tenant: Optional[TenantSpec] = None,
    ) -> AdmissionDecision:
        """Admit, queue, or shed one request. Priority: higher served first.

        ``deadline`` (optional end-to-end budget) caps the queue wait at
        the remaining budget; ``min_budget`` is the proxy's minimum viable
        attempt cost (connect-timeout floor) that the *dequeue* re-checks —
        a request granted its token with less budget than that left cannot
        complete and is shed as ``expired`` instead of forwarded.

        With tenant isolation configured, ``tenant`` routes the request
        through ITS OWN bucket and the weighted-fair queue instead of the
        shared bucket/heap — one tenant exhausting its share queues and
        sheds only its own traffic."""
        if not self.enabled:
            metrics.admitted_total.inc()
            if tenant is not None:
                self._count_tenant(self._tenant_admitted, tenant)
                metrics.tenant_admitted_total.labels(tenant=tenant.label).inc()
            return _ADMIT
        self._apply_share()
        if self._wfq is not None and tenant is not None:
            return await self._admit_tenant(tenant, deadline, min_budget)
        now = time.monotonic()
        if deadline is not None and deadline.expired():
            return self._shed("expired", 0.0)
        if not self._heap and self.bucket.try_acquire(now):
            metrics.admitted_total.inc()
            return _ADMIT
        queue_len = self.queue_len()
        if queue_len >= self.max_queue:
            return self._shed(
                "queue_full", self.bucket.time_until_tokens(queue_len + 1, now)
            )
        # The wait is bounded by the queue timeout AND the request's own
        # remaining budget — parking a 200ms-budget request for 5s of queue
        # timeout would just shed it later, at higher cost.
        wait_budget = self.queue_timeout
        if deadline is not None:
            wait_budget = min(wait_budget, max(deadline.remaining_s(), 0.0))
        # Deadline check up front: if the bucket cannot produce this
        # request's token before the deadline even in the best case, shed
        # now instead of parking doomed work in the queue. Only waiters the
        # dispatcher would serve first count toward the estimate — a
        # high-priority request must not be shed because the queue is full
        # of low-priority work it would jump.
        est = self.bucket.time_until_tokens(self._waiters_ahead(priority) + 1, now)
        if est > wait_budget:
            return self._shed("deadline", est)
        self._ensure_dispatcher()
        self._seq += 1
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        waiter = _Waiter(sort_key=(-priority, self._seq), future=fut)
        heapq.heappush(self._heap, waiter)
        metrics.queue_depth.set(self.queue_len())
        self._wakeup.set()
        try:
            await asyncio.wait_for(fut, timeout=wait_budget)
        except asyncio.TimeoutError:
            metrics.queue_depth.set(self.queue_len())
            # Distinguish WHY the wait ended: a wait capped by the
            # request's own budget is a deadline shed (504 upstream), not
            # a queue timeout (429 + Retry-After) — a client whose budget
            # is dead must not be told to retry later.
            if deadline is not None and (
                deadline.expired() or deadline.remaining_s() < min_budget
            ):
                return self._shed("expired", 0.0)
            return self._shed("timeout", self.bucket.time_until_tokens(1.0))
        # Dequeue re-check: the token was granted, but the wait may have
        # eaten the budget down to where no attempt can fit — forwarding
        # now would be doomed work the engine (or the proxy's own deadline
        # gate) sheds later anyway. Shed here, where it is cheapest.
        if deadline is not None and deadline.remaining_s() < min_budget:
            metrics.queue_depth.set(self.queue_len())
            return self._shed("expired", 0.0)
        metrics.admitted_total.inc()
        return _ADMIT

    async def _admit_tenant(
        self,
        tenant: TenantSpec,
        deadline: Optional[Deadline],
        min_budget: float,
    ) -> AdmissionDecision:
        """The tenant-isolated admission path: same shed taxonomy as the
        legacy path (queue_full / deadline / timeout / expired), but every
        estimate and every queue bound is computed against the tenant's
        OWN bucket and OWN queue — a flooding neighbor changes nothing
        here."""
        now = time.monotonic()
        if deadline is not None and deadline.expired():
            return self._shed("expired", 0.0, tenant)
        bucket = self.tenant_bucket(tenant)
        if not self._wfq.has_waiters(tenant.name) and bucket.try_acquire(now):
            metrics.admitted_total.inc()
            self._count_tenant(self._tenant_admitted, tenant)
            metrics.tenant_admitted_total.labels(tenant=tenant.label).inc()
            return _ADMIT
        depth = self._wfq.depth(tenant.name)
        if depth >= self.max_queue:
            # The bound is PER TENANT: a flooder fills its own queue and
            # sheds its own overflow; the victim's queue stays empty.
            return self._shed(
                "queue_full", bucket.time_until_tokens(depth + 1, now), tenant
            )
        wait_budget = self.queue_timeout
        if deadline is not None:
            wait_budget = min(wait_budget, max(deadline.remaining_s(), 0.0))
        est = bucket.time_until_tokens(depth + 1, now)
        if est > wait_budget:
            return self._shed("deadline", est, tenant)
        self._ensure_dispatcher()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._wfq.push(tenant.rank, tenant.name, fut)
        metrics.tenant_queue_depth.labels(tenant=tenant.label).set(
            self._wfq.depth(tenant.name)
        )
        metrics.queue_depth.set(self.queue_len())
        self._wakeup.set()
        try:
            await asyncio.wait_for(fut, timeout=wait_budget)
        except asyncio.TimeoutError:
            metrics.tenant_queue_depth.labels(tenant=tenant.label).set(
                self._wfq.depth(tenant.name)
            )
            metrics.queue_depth.set(self.queue_len())
            if deadline is not None and (
                deadline.expired() or deadline.remaining_s() < min_budget
            ):
                return self._shed("expired", 0.0, tenant)
            return self._shed(
                "timeout", bucket.time_until_tokens(1.0), tenant
            )
        if deadline is not None and deadline.remaining_s() < min_budget:
            metrics.queue_depth.set(self.queue_len())
            return self._shed("expired", 0.0, tenant)
        metrics.admitted_total.inc()
        self._count_tenant(self._tenant_admitted, tenant)
        metrics.tenant_admitted_total.labels(tenant=tenant.label).inc()
        return _ADMIT

    def _shed(
        self,
        reason: str,
        retry_after: float,
        tenant: Optional[TenantSpec] = None,
    ) -> AdmissionDecision:
        metrics.sheds_total.labels(reason=reason).inc()
        if tenant is not None:
            self._count_tenant(self._tenant_sheds, tenant)
            metrics.tenant_sheds_total.labels(
                tenant=tenant.label, reason=reason
            ).inc()
        return AdmissionDecision(
            admitted=False, reason=reason, retry_after=max(retry_after, 0.001)
        )

    @staticmethod
    def _count_tenant(table: Dict[str, int], tenant: TenantSpec) -> None:
        table[tenant.label] = table.get(tenant.label, 0) + 1

    def tenants_snapshot(self) -> Dict[str, dict]:
        """Per-tenant DRR/overload state for GET /debug/fleet: tier,
        weight, live queue depth, current bucket tokens, DRR deficit,
        and admitted/shed totals. Keys are the bounded metric labels
        (configured names verbatim, the ad-hoc population as "other"),
        so the snapshot — which gossips to every peer replica — can
        never grow with wire-invented tenant names."""
        if self.tenants is None:
            return {}
        out: Dict[str, dict] = {}
        names = set(self.tenants.tenants)
        names.update(self._tenant_buckets)
        if self._wfq is not None:
            names.update(name for _, name in self._wfq.tenants_waiting())
        for name in names:
            spec = self.tenants.spec_for(name)
            label = spec.label
            bucket = self._tenant_buckets.get(
                name if name in self.tenants.tenants else DEFAULT_TENANT
            )
            deficit = 0.0
            if self._wfq is not None:
                deficit = self._wfq._deficit.get((spec.rank, name), 0.0)
            row = out.get(label)
            if row is None:
                row = out[label] = {
                    "tier": spec.tier,
                    "weight": spec.weight,
                    "queue_depth": 0,
                    # Ad-hoc names all draw the DEFAULT bucket, so the
                    # collapsed row's tokens are consistent by design.
                    "bucket_tokens": (
                        round(bucket.tokens, 3) if bucket is not None
                        else None
                    ),
                    "drr_deficit": 0.0,
                    "admitted_total": self._tenant_admitted.get(label, 0),
                    "sheds_total": self._tenant_sheds.get(label, 0),
                }
            # The ad-hoc population collapses to one row, but its queue
            # and DRR state SUM across the underlying names — a flood of
            # invented names must show its real depth, not whichever
            # name set iteration happened to visit first.
            if self._wfq is not None:
                row["queue_depth"] += self._wfq.depth(name)
            row["drr_deficit"] = round(row["drr_deficit"] + deficit, 3)
        return out

    def close(self) -> None:
        if self._dispatcher is not None:
            self._dispatcher.cancel()
            self._dispatcher = None
        for w in self._heap:
            if not w.future.done():
                w.future.cancel()
        self._heap.clear()
        if self._wfq is not None:
            def _cancel(fut) -> bool:
                if not fut.done():
                    fut.cancel()
                return True

            self._wfq.discard(_cancel)
