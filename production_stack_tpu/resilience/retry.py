"""Retry/failover policy for the proxy hot path.

Only decides *whether* and *when* to try again; *where* stays with the
routing logic (the proxy re-routes among the remaining healthy candidates
on each attempt). The hard safety rule lives with the caller: never retry
after the first upstream byte has been streamed to the client.
"""

from __future__ import annotations


class RetryPolicy:
    def __init__(
        self,
        max_attempts: int = 3,
        backoff_base: float = 0.1,
        connect_timeout: float = 30.0,
        read_timeout: float = 0.0,
    ):
        self.max_attempts = max(1, max_attempts)
        self.backoff_base = backoff_base
        # Per-attempt upstream timeouts. A connect timeout is always safe
        # (TCP handshake only) and turns a black-holed backend into a
        # retryable failure. The read timeout bounds the gap between
        # socket reads — it catches an engine that accepted the request
        # and went silent, but would also abort a legitimately quiet
        # non-streamed long generation, so it defaults to off (0).
        self.connect_timeout = connect_timeout
        self.read_timeout = read_timeout

    def should_retry(self, attempt: int) -> bool:
        """``attempt`` is 0-based: attempt 0 is the first try."""
        return attempt + 1 < self.max_attempts

    def backoff(self, attempt: int) -> float:
        """Exponential backoff before attempt ``attempt + 1``."""
        return self.backoff_base * (2**attempt)

    @staticmethod
    def is_retryable_status(status: int) -> bool:
        """5xx before any byte reached the client = safe to re-route (the
        request never started executing a visible response). 4xx are the
        client's problem and must pass through."""
        return status >= 500
