"""End-to-end deadline/budget propagation + tail-latency request hedging.

The missing layer under the SLO target: PR 1's retries, admission queue,
and breakers all operate on *local* timeouts, so a request could be queued
at the router, retried, queued again in the engine scheduler, and finally
run long after the client gave up — burning TPU steps on dead work. This
module gives every hop the request's *remaining* latency budget (gRPC-style
deadline propagation) and lets the router hedge stragglers ("The Tail at
Scale"): after a quantile-based delay, a second attempt goes to the
next-best healthy engine and the first usable response wins.

Wire contract (documented in docs/resilience.md):

- ``X-PST-Deadline-Ms`` carries the remaining budget in milliseconds as a
  *relative* value (like gRPC's ``grpc-timeout``), not an absolute
  timestamp — clocks across hops never need to agree. Every hop converts
  it to a monotonic deadline on arrival and re-serializes the remainder
  when forwarding.
- ``X-PST-Deadline-Exceeded: 1`` tags every 504 produced by a deadline
  shed, wherever it happened (router admission, admission queue, proxy,
  engine admission, scheduler).

Deadlines ride ``time.monotonic()`` — wall-clock steps (NTP, leap smears)
must never extend or shrink a budget.
"""

from __future__ import annotations

import math
import time
from typing import Dict, List, Optional

DEADLINE_HEADER = "X-PST-Deadline-Ms"
DEADLINE_EXCEEDED_HEADER = "X-PST-Deadline-Exceeded"


class Deadline:
    """A monotonic deadline derived from a millisecond budget."""

    __slots__ = ("expires_at",)

    def __init__(self, budget_ms: float, now: Optional[float] = None):
        now = now if now is not None else time.monotonic()
        self.expires_at = now + budget_ms / 1000.0

    def remaining_s(self, now: Optional[float] = None) -> float:
        now = now if now is not None else time.monotonic()
        return self.expires_at - now

    def remaining_ms(self, now: Optional[float] = None) -> float:
        return self.remaining_s(now) * 1000.0

    def expired(self, now: Optional[float] = None) -> bool:
        return self.remaining_s(now) <= 0.0

    def header_value(self, now: Optional[float] = None) -> str:
        """Remaining budget for downstream propagation. Ceil, not floor: a
        live (not-yet-expired) deadline must never serialize to ``0``,
        which the next hop would shed on arrival."""
        return str(max(0, math.ceil(self.remaining_ms(now))))


def parse_deadline(
    headers, default_ms: float = 0.0, now: Optional[float] = None
) -> Optional[Deadline]:
    """Deadline from ``X-PST-Deadline-Ms`` (falling back to ``default_ms``;
    ``None`` when neither applies). Malformed or negative header values are
    ignored rather than erroring: a bad budget from one client must not
    turn into request failures."""
    raw = headers.get(DEADLINE_HEADER)
    if raw is None:  # plain dicts from tests may carry other casing
        lk = DEADLINE_HEADER.lower()
        for k, v in headers.items():
            if k.lower() == lk:
                raw = v
                break
    if raw is not None:
        try:
            budget = float(raw)
            if budget >= 0:
                return Deadline(budget, now)
        except (TypeError, ValueError):
            pass
    if default_ms and default_ms > 0:
        return Deadline(default_ms, now)
    return None


def min_attempt_budget(policy) -> float:
    """The budget floor below which forwarding (or retrying) is doomed
    work: an attempt that cannot even fit the connect timeout inside the
    remaining budget is guaranteed to blow the deadline. Deployments that
    hand out tight budgets should set ``--proxy-connect-timeout``
    comparable to real connect latency — the gates treat it as the
    minimum viable attempt cost."""
    if policy is None:
        return 0.0
    return float(policy.connect_timeout or 0.0)


class LatencyTracker:
    """Bounded reservoir of recent request latencies for quantile-based
    hedge delays. Insertion is O(1); ``quantile`` sorts the (small) window
    on demand — called once per hedge-eligible request."""

    def __init__(self, window: int = 256):
        self.window = max(8, window)
        # pstlint: owned-by=task:observe
        self._samples: List[float] = []
        self._idx = 0

    def observe(self, latency_s: float) -> None:
        if len(self._samples) < self.window:
            self._samples.append(latency_s)
        else:
            self._samples[self._idx] = latency_s
            self._idx = (self._idx + 1) % self.window

    def __len__(self) -> int:
        return len(self._samples)

    def quantile(self, q: float) -> Optional[float]:
        if not self._samples:
            return None
        ordered = sorted(self._samples)
        pos = min(len(ordered) - 1, max(0, math.ceil(q * len(ordered)) - 1))
        return ordered[pos]


class HedgePolicy:
    """When and whether the router may issue a tail-latency hedge.

    - ``delay_ms > 0``: fixed hedge trigger delay.
    - ``delay_ms == 0``: quantile-based — the delay tracks the observed
      ``quantile`` of recent hedge-eligible latencies (Tail-at-Scale's
      "defer to the p9x"), bounded below by ``min_delay_ms`` and falling
      back to ``fallback_delay_ms`` until enough samples exist.
    - ``max_outstanding_ratio`` caps outstanding hedges at
      ``ceil(ratio * outstanding primaries)`` (floor 1, so a lone slow
      request can still hedge) — hedging can *shift* load to healthy
      engines but must never double fleet load during an incident.
    """

    def __init__(
        self,
        enabled: bool = False,
        delay_ms: float = 0.0,
        quantile: float = 0.9,
        max_outstanding_ratio: float = 0.25,
        min_delay_ms: float = 10.0,
        fallback_delay_ms: float = 100.0,
        min_samples: int = 16,
    ):
        self.enabled = enabled
        self.delay_ms = delay_ms
        self.quantile = quantile
        self.max_outstanding_ratio = max(0.0, max_outstanding_ratio)
        self.min_delay_ms = min_delay_ms
        self.fallback_delay_ms = fallback_delay_ms
        self.min_samples = min_samples
        self.tracker = LatencyTracker()
        self.outstanding_primaries = 0
        self.outstanding_hedges = 0

    # -- delay -------------------------------------------------------------

    def delay_s(self) -> float:
        if self.delay_ms > 0:
            return self.delay_ms / 1000.0
        if len(self.tracker) >= self.min_samples:
            q = self.tracker.quantile(self.quantile)
            if q is not None:
                return max(q, self.min_delay_ms / 1000.0)
        return self.fallback_delay_ms / 1000.0

    def observe_latency(self, latency_s: float) -> None:
        self.tracker.observe(latency_s)

    # -- accounting --------------------------------------------------------

    def note_request_start(self) -> None:
        self.outstanding_primaries += 1

    def note_request_end(self) -> None:
        self.outstanding_primaries = max(0, self.outstanding_primaries - 1)

    def try_acquire_hedge(self) -> bool:
        cap = max(1, math.ceil(self.max_outstanding_ratio * self.outstanding_primaries))
        if self.outstanding_hedges >= cap:
            return False
        self.outstanding_hedges += 1
        return True

    def release_hedge(self) -> None:
        self.outstanding_hedges = max(0, self.outstanding_hedges - 1)


def with_deadline_header(
    headers: Dict[str, str], deadline: Optional[Deadline]
) -> Dict[str, str]:
    """Copy of ``headers`` carrying the *current* remaining budget — called
    per attempt, so each retry/hedge/leg sees a smaller budget."""
    if deadline is None:
        return headers
    out = dict(headers)
    out[DEADLINE_HEADER] = deadline.header_value()
    return out
