"""Resilience subsystem: circuit breakers, retry/failover, admission control.

The reference stack leans on Envoy (outlier detection, retries) and K8s
probes for production survivability; this stack serves straight from the
router, so the protections live here natively:

- :mod:`breaker` — per-backend circuit breakers (closed/open/half-open,
  keyed by engine URL) fed by proxy outcomes and health probes; routing
  consults them before picking an engine.
- :mod:`admission` — token-bucket rate limiting plus a bounded priority
  queue with deadline-based load shedding (429 + ``Retry-After``) ahead
  of ``route_general_request``.
- :mod:`retry` — backoff schedule for proxy retry/failover (only ever
  before the first streamed byte reaches the client).
- :mod:`deadline` — end-to-end deadline/budget propagation
  (``X-PST-Deadline-Ms``) and the tail-latency hedging policy.
- :mod:`stream_resume` — SSE journaling + transparent mid-stream
  failover: a stream broken by engine death is continued on another
  engine and spliced seamlessly into the client response.
- :mod:`metrics` — the ``pst_resilience_*`` / ``pst_deadline_*`` /
  ``pst_hedge_*`` Prometheus surface.

Lifecycle mirrors the other router singletons (initialize/get/teardown);
``get_*`` accessors return ``None`` when the subsystem is not configured
so every caller degrades to the pre-resilience behavior.
"""

from __future__ import annotations

from typing import Optional

from .admission import AdmissionController
from .breaker import BreakerState, CircuitBreaker, CircuitBreakerRegistry
from .deadline import (
    DEADLINE_EXCEEDED_HEADER,
    DEADLINE_HEADER,
    Deadline,
    HedgePolicy,
    parse_deadline,
)
from .retry import RetryPolicy
from .stream_resume import StreamResumePolicy
from .tenancy import (
    TENANT_CLASS_HEADER,
    TENANT_HEADER,
    TIER_BATCH,
    TIER_INTERACTIVE,
    TenantConfig,
    TenantSpec,
)

_breaker_registry: Optional[CircuitBreakerRegistry] = None
_admission_controller: Optional[AdmissionController] = None
_retry_policy: Optional[RetryPolicy] = None
_hedge_policy: Optional[HedgePolicy] = None
_stream_resume_policy: Optional[StreamResumePolicy] = None
_default_deadline_ms: float = 0.0
_tenant_config: Optional[TenantConfig] = None


def _build_tenant_config(args) -> Optional[TenantConfig]:
    """TenantConfig from parsed router args (None = tenancy off: every
    layer behaves exactly as before tenants existed)."""
    if not getattr(args, "tenant_isolation", False):
        return None
    path = getattr(args, "tenant_config", None)
    kwargs = dict(
        default_weight=float(getattr(args, "tenant_default_weight", 1.0)),
        default_tier=getattr(args, "tenant_default_tier", TIER_INTERACTIVE),
        header=getattr(args, "tenant_header", TENANT_HEADER),
    )
    if path:
        return TenantConfig.from_file(path, **kwargs)
    return TenantConfig(**kwargs)


def initialize_resilience(args) -> None:
    """Create the resilience singletons from parsed router args."""
    global _breaker_registry, _admission_controller, _retry_policy
    global _hedge_policy, _stream_resume_policy, _default_deadline_ms
    global _tenant_config
    # Router HA: breakers and admission coordinate across replicas through
    # the state backend (None / in-memory = exact single-replica behavior).
    from ..router.state import PROVIDER_BREAKERS, get_state_backend

    backend = get_state_backend()
    _tenant_config = _build_tenant_config(args)
    _breaker_registry = CircuitBreakerRegistry(
        failure_threshold=getattr(args, "breaker_failure_threshold", 5),
        recovery_time=getattr(args, "breaker_recovery_time", 10.0),
        half_open_probes=getattr(args, "breaker_half_open_probes", 1),
        state_backend=backend,
    )
    if backend is not None:
        registry = _breaker_registry
        backend.register_provider(PROVIDER_BREAKERS, registry.snapshot)
    _admission_controller = AdmissionController(
        rate=getattr(args, "admission_rate", 0.0),
        burst=getattr(args, "admission_burst", 0),
        max_queue=getattr(args, "admission_queue_size", 128),
        queue_timeout=getattr(args, "admission_queue_timeout", 5.0),
        state_backend=backend,
        tenants=_tenant_config,
    )
    _retry_policy = RetryPolicy(
        max_attempts=getattr(args, "proxy_retries", 2) + 1,
        backoff_base=getattr(args, "retry_backoff", 0.1),
        connect_timeout=getattr(args, "proxy_connect_timeout", 30.0),
        read_timeout=getattr(args, "proxy_read_timeout", 0.0),
    )
    _default_deadline_ms = float(getattr(args, "default_deadline_ms", 0) or 0)
    _hedge_policy = HedgePolicy(
        enabled=bool(getattr(args, "hedge_enabled", False)),
        delay_ms=float(getattr(args, "hedge_delay_ms", 0.0) or 0.0),
        quantile=float(getattr(args, "hedge_quantile", 0.9)),
        max_outstanding_ratio=float(
            getattr(args, "hedge_max_outstanding_ratio", 0.25)
        ),
    )
    _stream_resume_policy = StreamResumePolicy(
        enabled=bool(getattr(args, "stream_resume", False)),
        max_legs=int(getattr(args, "stream_resume_max_legs", 2) or 2),
    )


def get_breaker_registry() -> Optional[CircuitBreakerRegistry]:
    return _breaker_registry


def get_admission_controller() -> Optional[AdmissionController]:
    return _admission_controller


def get_retry_policy() -> Optional[RetryPolicy]:
    return _retry_policy


def get_hedge_policy() -> Optional[HedgePolicy]:
    return _hedge_policy


def get_stream_resume_policy() -> Optional[StreamResumePolicy]:
    return _stream_resume_policy


def get_default_deadline_ms() -> float:
    return _default_deadline_ms


def get_tenant_config() -> Optional[TenantConfig]:
    return _tenant_config


def teardown_resilience() -> None:
    global _breaker_registry, _admission_controller, _retry_policy
    global _hedge_policy, _stream_resume_policy, _default_deadline_ms
    global _tenant_config
    if _admission_controller is not None:
        _admission_controller.close()
    _breaker_registry = None
    _admission_controller = None
    _retry_policy = None
    _hedge_policy = None
    _stream_resume_policy = None
    _default_deadline_ms = 0.0
    _tenant_config = None


__all__ = [
    "AdmissionController",
    "BreakerState",
    "CircuitBreaker",
    "CircuitBreakerRegistry",
    "DEADLINE_EXCEEDED_HEADER",
    "DEADLINE_HEADER",
    "Deadline",
    "HedgePolicy",
    "RetryPolicy",
    "StreamResumePolicy",
    "TENANT_CLASS_HEADER",
    "TENANT_HEADER",
    "TIER_BATCH",
    "TIER_INTERACTIVE",
    "TenantConfig",
    "TenantSpec",
    "initialize_resilience",
    "get_breaker_registry",
    "get_admission_controller",
    "get_retry_policy",
    "get_hedge_policy",
    "get_stream_resume_policy",
    "get_default_deadline_ms",
    "get_tenant_config",
    "parse_deadline",
    "teardown_resilience",
]
