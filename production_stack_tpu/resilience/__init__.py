"""Resilience subsystem: circuit breakers, retry/failover, admission control.

The reference stack leans on Envoy (outlier detection, retries) and K8s
probes for production survivability; this stack serves straight from the
router, so the protections live here natively:

- :mod:`breaker` — per-backend circuit breakers (closed/open/half-open,
  keyed by engine URL) fed by proxy outcomes and health probes; routing
  consults them before picking an engine.
- :mod:`admission` — token-bucket rate limiting plus a bounded priority
  queue with deadline-based load shedding (429 + ``Retry-After``) ahead
  of ``route_general_request``.
- :mod:`retry` — backoff schedule for proxy retry/failover (only ever
  before the first streamed byte reaches the client).
- :mod:`metrics` — the ``pst_resilience_*`` Prometheus surface.

Lifecycle mirrors the other router singletons (initialize/get/teardown);
``get_*`` accessors return ``None`` when the subsystem is not configured
so every caller degrades to the pre-resilience behavior.
"""

from __future__ import annotations

from typing import Optional

from .admission import AdmissionController
from .breaker import BreakerState, CircuitBreaker, CircuitBreakerRegistry
from .retry import RetryPolicy

_breaker_registry: Optional[CircuitBreakerRegistry] = None
_admission_controller: Optional[AdmissionController] = None
_retry_policy: Optional[RetryPolicy] = None


def initialize_resilience(args) -> None:
    """Create the resilience singletons from parsed router args."""
    global _breaker_registry, _admission_controller, _retry_policy
    _breaker_registry = CircuitBreakerRegistry(
        failure_threshold=getattr(args, "breaker_failure_threshold", 5),
        recovery_time=getattr(args, "breaker_recovery_time", 10.0),
        half_open_probes=getattr(args, "breaker_half_open_probes", 1),
    )
    _admission_controller = AdmissionController(
        rate=getattr(args, "admission_rate", 0.0),
        burst=getattr(args, "admission_burst", 0),
        max_queue=getattr(args, "admission_queue_size", 128),
        queue_timeout=getattr(args, "admission_queue_timeout", 5.0),
    )
    _retry_policy = RetryPolicy(
        max_attempts=getattr(args, "proxy_retries", 2) + 1,
        backoff_base=getattr(args, "retry_backoff", 0.1),
        connect_timeout=getattr(args, "proxy_connect_timeout", 30.0),
        read_timeout=getattr(args, "proxy_read_timeout", 0.0),
    )


def get_breaker_registry() -> Optional[CircuitBreakerRegistry]:
    return _breaker_registry


def get_admission_controller() -> Optional[AdmissionController]:
    return _admission_controller


def get_retry_policy() -> Optional[RetryPolicy]:
    return _retry_policy


def teardown_resilience() -> None:
    global _breaker_registry, _admission_controller, _retry_policy
    if _admission_controller is not None:
        _admission_controller.close()
    _breaker_registry = None
    _admission_controller = None
    _retry_policy = None


__all__ = [
    "AdmissionController",
    "BreakerState",
    "CircuitBreaker",
    "CircuitBreakerRegistry",
    "RetryPolicy",
    "initialize_resilience",
    "get_breaker_registry",
    "get_admission_controller",
    "get_retry_policy",
    "teardown_resilience",
]
