"""Consistent-hash ring shared by the router and the KV-store client.

Originally private to :mod:`production_stack_tpu.router.routing.logic`
(which still re-exports it); hoisted to a dependency-free module so the
sharded KV client (:mod:`production_stack_tpu.kvserver.sharded`), the
kvserver's anti-entropy sweep and the fake engine can compute the SAME
(key -> owner set) placement as the router without importing the router's
discovery/scoring stack into the engine process. One placement function
across every process is what makes replica sets agree: a block published
by the prefill engine is looked up on the same owners by the decode
engine, the router's KV-aware scorer and the shard's own sweep.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence, Tuple

import xxhash


class ConsistentHashRing:
    """xxhash-based ring with virtual nodes; minimal remapping on membership change."""

    def __init__(self, vnodes: int = 160):
        self.vnodes = vnodes
        # pstlint: owned-by=task:update,_rebuild
        self._nodes: set = set()
        # pstlint: owned-by=task:update,_rebuild
        self._ring: List[Tuple[int, str]] = []
        # pstlint: owned-by=task:update,_rebuild
        self._hashes: List[int] = []

    def _rebuild(self) -> None:
        ring = []
        for node in self._nodes:
            for v in range(self.vnodes):
                ring.append((xxhash.xxh64_intdigest(f"{node}#{v}"), node))
        ring.sort()
        self._ring = ring
        self._hashes = [h for h, _ in ring]

    def update(self, nodes: Sequence[str]) -> None:
        new = set(nodes)
        if new != self._nodes:
            self._nodes = new
            self._rebuild()

    def get_node(self, key: str) -> Optional[str]:
        if not self._ring:
            return None
        h = xxhash.xxh64_intdigest(key)
        idx = bisect.bisect(self._hashes, h) % len(self._ring)
        return self._ring[idx][1]

    def get_nodes(self, key: str, n: int) -> List[str]:
        """The first ``n`` DISTINCT nodes clockwise from ``key``'s ring
        position — the replica owner set for replication factor ``n``.
        ``get_nodes(key, 1)[0] == get_node(key)``, and because the walk
        order is the ring order, adding one node to the ring shifts each
        key's owner list by at most one position: an R-replicated block
        keeps at least one pre-join owner in its post-join owner set for
        R >= 2, which is what keeps published blocks findable across a
        shard join (tests/test_kvserver_ring.py)."""
        if not self._ring or n <= 0:
            return []
        h = xxhash.xxh64_intdigest(key)
        start = bisect.bisect(self._hashes, h) % len(self._ring)
        owners: List[str] = []
        seen: set = set()
        for i in range(len(self._ring)):
            node = self._ring[(start + i) % len(self._ring)][1]
            if node in seen:
                continue
            seen.add(node)
            owners.append(node)
            if len(owners) >= n or len(seen) == len(self._nodes):
                break
        return owners

    def get_node_bounded(
        self,
        key: str,
        loads: Dict[str, float],
        c: float = 2.0,
        allowed: Optional[set] = None,
    ) -> Optional[str]:
        """Consistent hashing with bounded loads (Mirrokni et al.): walk
        the ring clockwise from ``key``'s position and take the first
        node whose current load is under ``c ×`` the mean load, falling
        back to the first eligible node when everything is saturated.
        Replicated routers use this over the *shared* endpoint view +
        fleet-wide stats, so every replica computes the same (key → node)
        map AND a hot-spotted node sheds to the same successor on every
        replica.

        ``allowed`` constrains the pick to THIS replica's routable
        candidates (model match, not draining/sleeping, breaker-admitted)
        while the ring still hashes over the shared fleet view: replicas
        whose candidate sets agree pick identically, and a replica whose
        discovery lags simply walks to the nearest node it can actually
        route to — it never picks an engine it must not use."""
        if not self._ring:
            return None
        candidates = (
            self._nodes if allowed is None else self._nodes & set(allowed)
        )
        if not candidates:
            return None
        mean = sum(loads.get(n, 0.0) for n in candidates) / len(candidates)
        bound = c * max(mean, 1.0)
        h = xxhash.xxh64_intdigest(key)
        start = bisect.bisect(self._hashes, h) % len(self._ring)
        first_eligible: Optional[str] = None
        seen: set = set()
        for i in range(len(self._ring)):
            node = self._ring[(start + i) % len(self._ring)][1]
            if node in seen:
                continue
            seen.add(node)
            if node not in candidates:
                continue
            if first_eligible is None:
                first_eligible = node
            if loads.get(node, 0.0) < bound:
                return node
            if len(seen) == len(self._nodes):
                break
        return first_eligible
