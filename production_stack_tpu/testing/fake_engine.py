"""Fake serving engine: the backend for router tests without TPUs.

Capability parity with the reference's
``src/tests/perftest/fake-openai-server.py`` (streams tokens at a
configurable rate, tracks running-request count) extended to the full
surface the router depends on (SURVEY.md §4 "pattern to replicate"):
``/v1/models``, ``/v1/chat/completions``, ``/v1/completions`` (streaming
and non-streaming), ``/metrics`` with ``vllm:``-style gauges,
``/is_sleeping`` + ``/sleep`` + ``/wake_up``, ``/health``, ``/ready``
(simulated warmup precompilation: ``--ready-delay`` + a warm-restart
cache-dir marker), LoRA load/unload endpoints, and ``/tokenize``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import re
import time
import uuid
from collections import OrderedDict
from typing import List, Optional

import aiohttp
import xxhash
from aiohttp import web

from ..logging_utils import init_logger
from ..obs import (
    bind_log_context,
    configure_logging,
    observe_stage,
    parse_traceparent,
    render_obs_metrics,
    unbind_log_context,
)

logger = init_logger(__name__)

# Simulated lattice size: what the warmup metrics (coverage, cache
# hits/misses) count against. Arbitrary but deterministic.
FAKE_WARMUP_BUCKETS = 12

# Fraction of the cold ready delay a warm restart pays (the persistent
# cache skips XLA but tracing/deserialization still cost something).
_WARM_RESTART_FRACTION = 0.2

# Simulated prefix-cache granularity: chars per KV chunk and the token
# mass one chunk represents (~the real engine's block-size granularity;
# the fake "tokenizer" is ~4 chars/token).
KV_CHUNK_CHARS = 32
KV_CHUNK_TOKENS = 8
# Working KV a running sequence holds beyond its cached prefix (rough —
# drives occupancy up under concurrency the way live decode state does).
KV_RUNNING_TOKENS = 64


def kv_chunk_hashes(text: str) -> List[int]:
    """Prefix-committing chain hashes over fixed char windows: chunk i's
    hash commits to everything before it, so a match on chunk i implies
    the whole prefix matches — the same property the real chunk-hash
    scheme (kvcache/hashing.py) has."""
    out: List[int] = []
    h = 0
    for i in range(0, len(text), KV_CHUNK_CHARS):
        h = xxhash.xxh64_intdigest(f"{h:x}:{text[i:i + KV_CHUNK_CHARS]}")
        out.append(h)
    return out


class FakeEngineState:
    def __init__(self, model: str, speed: float, max_tokens_default: int = 32,
                 kv_capacity_tokens: int = 20000, kv_url: Optional[str] = None,
                 kv_replication: int = 2):
        self.model = model
        self.speed = speed  # tokens per second
        self.max_tokens_default = max_tokens_default
        # Streamed disagg KV handoff (docs/disagg.md): with a kvserver URL
        # configured, a producer-leg generation publishes deterministic
        # block manifests + pages per simulated prefill chunk, and a
        # consumer-leg generation follows the manifest and batch-fetches
        # them BEFORE decoding — the real handoff protocol without TPUs.
        # A comma-separated URL list makes this a sharded-ring client with
        # the same placement/replication/read-repair semantics as the real
        # engine's ShardedKVClient (docs/kvserver.md) — what the
        # kv_shard_kill chaos leg drives.
        self.kv_urls = [
            u.strip().rstrip("/")
            for u in (kv_url or "").split(",") if u.strip()
        ]
        self.kv_url = self.kv_urls[0] if self.kv_urls else None
        self.kv_replication = (
            min(max(int(kv_replication), 1), len(self.kv_urls))
            if self.kv_urls else 0
        )
        self.kv_ring = None
        if len(self.kv_urls) > 1:
            from ..hashring import ConsistentHashRing

            self.kv_ring = ConsistentHashRing()
            self.kv_ring.update(self.kv_urls)
        self.kv_transfer_timeout = 5.0
        self.kv_published_blocks = 0
        self.kv_prefetched_blocks = 0
        self.kv_transfer_fallbacks = 0
        self.kv_read_repairs = 0
        self.kv_integrity_failures = 0

        self.manifest_fetches = 0
        self.kv_publish_chunks = 3  # simulated prefill chunk count
        self.kv_chunk_delay = 0.02  # seconds between chunk publishes
        # Opt-in chip queueing model (--chip-ms-per-ktok; bench's disagg
        # phase): one "chip" per engine processes slices FIFO — a prefill
        # is one big exclusive slice (this many ms per 1000 prompt
        # tokens), each decode token a small one. On a fused engine every
        # prefill queues behind in-flight decode slices and vice versa —
        # exactly the head-of-line interference P/D disaggregation
        # removes. A consumer leg whose prefetch completed pays only a
        # tail slice (10%): its prefix KV arrived over the wire. 0 = off
        # (the legacy instant-concurrency behavior every other test
        # relies on).
        self.chip_ms_per_ktok = 0.0
        self.num_running = 0
        self.num_waiting = 0
        # Token-weighted prefix-cache accounting, fed by the simulated
        # paged KV below (was: hardcoded zeros) — hit rate really reflects
        # whether this engine served this conversation before.
        self.prefix_hits = 0
        self.prefix_queries = 0
        # Simulated paged KV cache: chunk hash -> token mass, LRU order.
        # Occupancy derives from what is actually cached + running, so
        # routing tests exercise real headroom dynamics instead of
        # min(1, num_running * 0.1).
        self.kv_capacity_tokens = max(int(kv_capacity_tokens), 1)
        self.kv_chunks: "OrderedDict[int, int]" = OrderedDict()
        self.kv_tokens = 0
        # /admin/fill_kv: reported-occupancy floor for headroom-spill
        # tests that need an engine pinned "full" without traffic.
        self.kv_fill_floor = 0.0
        self.sleeping = False
        self.sleep_level: Optional[str] = None
        self.lora_adapters: List[str] = []
        self.requests_seen: List[dict] = []
        # Fault injection (resilience tests): POST /admin/fail arms one of
        #   error — respond fail_status (default 500) immediately
        #   transfer — break the disagg KV handoff only: a producer leg
        #           publishes nothing (its manifest never completes) and a
        #           consumer leg finds nothing — both degrade to the fused
        #           path and count kv_transfer_fallbacks; the generation
        #           itself still succeeds (no client-visible error)
        #   hang  — accept the request and never answer
        #   midstream — stream fail_after_chunks delta chunks, then drop
        #               the connection (tests the never-replay-after-
        #               first-byte rule and stream resumption; 0 = die
        #               before any delta, >= max_tokens = die after the
        #               last delta but before [DONE])
        #   slow  — inject fail_delay (+ up to fail_jitter) seconds of
        #           latency before answering, honoring the propagated
        #           X-PST-Deadline-Ms budget: when the injected delay would
        #           blow the budget, reply 504 + X-PST-Deadline-Exceeded at
        #           the deadline instead (deterministic hedging/shedding
        #           tests)
        # fail_count > 0 limits the fault to the next N generations
        # (auto-heal); -1 = until POST /admin/heal.
        # fail_tenant scopes the fault to requests carrying that
        # X-PST-Tenant value (isolation chaos legs fault one tenant's
        # traffic without touching the victim's; None = every request).
        self.fail_mode: Optional[str] = None
        self.fail_status = 500
        self.fail_count = -1
        self.fail_delay = 0.5
        self.fail_jitter = 0.0
        self.fail_tenant: Optional[str] = None
        # Delta chunks delivered before a `midstream` death (default 3,
        # the legacy hardcoded behavior).
        self.fail_after_chunks = 3
        self.num_faulted = 0
        # Graceful drain: new generations 503, in-flight ones finish.
        self.draining = False
        # X-PST-Deadline-Ms header value (or None) per generation request,
        # in arrival order — lets tests assert budget propagation/decay.
        self.deadlines_seen: List[Optional[str]] = []
        # (traceparent, X-Request-Id) per generation request, in arrival
        # order — lets e2e tests assert one trace id spans every leg
        # (primary, retries, hedges) across engines.
        self.traces_seen: List[dict] = []
        # (X-PST-Tenant, X-PST-Tenant-Class) per generation request, in
        # arrival order — lets tests assert the router's tenant stamp
        # reached the engine on every hop.
        self.tenants_seen: List[dict] = []
        # Deterministic flight-recorder ring (the real engine's
        # GET /debug/flight contract, docs/observability.md "Flight
        # recorder"): every generation appends one prefill + one decode
        # record with values derived from the request, so router-side
        # flight/capacity tests run engine-free and byte-reproducibly.
        self.flight_records: List[dict] = []
        self.flight_capacity = 128
        self.flight_total = 0
        # Retained flight snapshots (the real recorder's snapshot_log
        # contract): the `stall` fault appends a deterministic
        # tail_outlier snapshot naming the stalled step's bucket and
        # queue depths, so forensics tests induce the BENCH_r05
        # signature on CPU. With a flight_snapshot_dir set, each
        # snapshot is also persisted (same file naming as
        # obs/flight.py) so post-mortem collection works after SIGKILL.
        self.flight_snapshots: List[dict] = []
        self.flight_snapshot_keep = 8
        self.flight_snapshot_dir: Optional[str] = None
        self.restored_snapshots: List[dict] = []
        self._snapshot_seq = 0
        # Simulated warmup precompilation (the real engine's /ready
        # contract): the engine reports warming for ``ready_delay``
        # seconds after start. With a ``warmup_cache_dir``, a marker file
        # left by a previous instance makes this a WARM restart — the
        # delay shrinks to a fraction and the deterministic cache
        # counters flip from all-misses to all-hits, so router-discovery
        # and restart e2e tests run the full story without a TPU.
        self.ready_delay = 0.0
        self.warmup_cache_dir: Optional[str] = None
        self.warm_start = False
        self.warmup_started = time.monotonic()
        self._marker_written = False

    def kv_owners(self, key) -> List[str]:
        """A block/manifest key's R-member replica owner set (the whole
        "fleet" when single-shard — identical to the pre-ring behavior)."""
        if self.kv_ring is None:
            return list(self.kv_urls)
        return self.kv_ring.get_nodes(str(key), self.kv_replication)

    def kv_walk(self, key) -> List[str]:
        """Ring-order read walk (owners first, then every other shard)."""
        if self.kv_ring is None:
            return list(self.kv_urls)
        return self.kv_ring.get_nodes(str(key), len(self.kv_urls))

    def configure_warmup(
        self, ready_delay: float, cache_dir: Optional[str] = None
    ) -> None:
        self.ready_delay = max(float(ready_delay), 0.0)
        self.warmup_cache_dir = cache_dir
        self.warm_start = bool(
            cache_dir and os.path.exists(os.path.join(cache_dir, "warm"))
        )
        self.warmup_started = time.monotonic()
        self._marker_written = False

    @property
    def effective_ready_delay(self) -> float:
        return self.ready_delay * (
            _WARM_RESTART_FRACTION if self.warm_start else 1.0
        )

    @property
    def warming(self) -> bool:
        warming = (
            time.monotonic() - self.warmup_started
            < self.effective_ready_delay
        )
        if not warming and self.warmup_cache_dir and not self._marker_written:
            # Ready (first observation): persist the cache marker once so
            # the next instance with this cache dir restarts warm (the
            # PVC/hostPath analogue).
            self._marker_written = True
            try:
                os.makedirs(self.warmup_cache_dir, exist_ok=True)
                with open(
                    os.path.join(self.warmup_cache_dir, "warm"), "w"
                ) as f:
                    f.write(self.model)
            except OSError:  # pragma: no cover — read-only fixture dirs
                pass
        return warming

    @property
    def warmup_coverage(self) -> float:
        if self.effective_ready_delay <= 0:
            return 1.0
        elapsed = time.monotonic() - self.warmup_started
        return min(elapsed / self.effective_ready_delay, 1.0)

    def account_prefix(self, prompt_text: str) -> int:
        """One generation's prefix-cache pass: count token-weighted hits
        against the simulated KV, then cache the prompt's chunks (LRU
        eviction at capacity). Returns matched chunk count."""
        hashes = kv_chunk_hashes(prompt_text)
        matched = 0
        for h in hashes:
            if h in self.kv_chunks:
                matched += 1
                self.kv_chunks.move_to_end(h)
            else:
                break  # chain hashes: first miss ends the match
        self.prefix_queries += len(hashes) * KV_CHUNK_TOKENS
        self.prefix_hits += matched * KV_CHUNK_TOKENS
        for h in hashes[matched:]:
            # A chunk past the first miss can still be cached (partial
            # LRU eviction left a hole): re-inserting it must not count
            # its token mass twice, or occupancy ratchets upward forever.
            if h not in self.kv_chunks:
                self.kv_tokens += KV_CHUNK_TOKENS
            self.kv_chunks[h] = KV_CHUNK_TOKENS
            self.kv_chunks.move_to_end(h)
        while self.kv_tokens > self.kv_capacity_tokens and self.kv_chunks:
            _, tokens = self.kv_chunks.popitem(last=False)
            self.kv_tokens -= tokens
        return matched

    @property
    def kv_occupancy(self) -> float:
        """Derived KV page occupancy: cached chunks + live decode state,
        floored by the /admin/fill_kv override."""
        live = self.kv_tokens + self.num_running * KV_RUNNING_TOKENS
        derived = min(live / self.kv_capacity_tokens, 1.0)
        return max(derived, min(max(self.kv_fill_floor, 0.0), 1.0))

    def fake_cost(self, prompt_tokens: int, n_tokens: int) -> dict:
        """Deterministic X-PST-Cost payload: the real engine's field set
        with values derived purely from token counts, so router/billing
        tests assert exact numbers."""
        prefill = round(prompt_tokens * 1e-4, 6)
        decode = round(n_tokens * 1e-3, 6)
        return {
            "prefill_device_s": prefill,
            "decode_device_s": decode,
            "device_s": round(prefill + decode, 6),
            "kv_page_s": round((prompt_tokens + n_tokens) * 0.01, 3),
            "queue_s": 0.0,
        }

    def record_flight(self, prompt_tokens: int, n_tokens: int) -> None:
        """Two deterministic ring records per generation (the prefill
        step and its decode burst), same field set as obs/flight.py."""
        base = {
            "ts": time.time(),
            "host_gap_s": 0.0005,
            "compiled": False,
            "waiting": self.num_waiting,
            "running": self.num_running,
            "swapped": 0,
            "kv_occupancy": round(self.kv_occupancy, 4),
            "preemptions": 0,
            "batch_tier_rows": 0,
        }
        self.flight_records.append({
            **base, "kind": "prefill",
            "bucket": f"b1xt{max(prompt_tokens, 1)}",
            "device_s": round(prompt_tokens * 1e-4, 6),
            "tokens": prompt_tokens,
        })
        self.flight_records.append({
            **base, "kind": "decode",
            "bucket": f"b{max(self.num_running, 1)}xn{max(n_tokens, 1)}",
            "device_s": round(n_tokens * 1e-3, 6),
            "tokens": n_tokens,
        })
        self.flight_total += 2
        if len(self.flight_records) > self.flight_capacity:
            del self.flight_records[: len(self.flight_records)
                                    - self.flight_capacity]

    def record_stall(self, stall_s: float, n_tokens: int) -> None:
        """One stalled decode step: an extra ring record whose device_s
        is the injected stall, plus a retained tail_outlier snapshot
        naming the stalled bucket and queue state — the same evidence
        the real recorder leaves for an unexplained p99 (obs/flight.py
        auto-snapshot contract)."""
        bucket = f"b{max(self.num_running, 1)}xn{max(n_tokens, 1)}"
        baseline_s = max(n_tokens, 1) * 1e-3  # the unstalled decode cost
        row = {
            "ts": time.time(),
            "kind": "decode",
            "bucket": bucket,
            "device_s": round(stall_s, 6),
            "host_gap_s": 0.0005,
            "compiled": False,
            "waiting": self.num_waiting,
            "running": self.num_running,
            "swapped": 0,
            "kv_occupancy": round(self.kv_occupancy, 4),
            "preemptions": 0,
            "batch_tier_rows": 0,
            "tokens": n_tokens,
        }
        self.flight_records.append(row)
        self.flight_total += 1
        if len(self.flight_records) > self.flight_capacity:
            del self.flight_records[: len(self.flight_records)
                                    - self.flight_capacity]
        snap = {
            "reason": "tail_outlier",
            "ts": time.time(),
            "detail": {
                "kind": "decode",
                "bucket": bucket,
                "device_s": round(stall_s, 6),
                "bar_s": round(baseline_s * 3.0, 6),
                "waiting": self.num_waiting,
                "running": self.num_running,
                "swapped": 0,
                "kv_occupancy": round(self.kv_occupancy, 4),
                "injected": "stall",
            },
            "total_steps": self.flight_total,
            "records": list(self.flight_records[-16:]),
        }
        self.flight_snapshots.append(snap)
        if len(self.flight_snapshots) > self.flight_snapshot_keep:
            del self.flight_snapshots[: len(self.flight_snapshots)
                                      - self.flight_snapshot_keep]
        d = self.flight_snapshot_dir
        if d:
            try:
                os.makedirs(d, exist_ok=True)
                self._snapshot_seq += 1
                name = (f"flight_{time.time_ns():020d}_"
                        f"{self._snapshot_seq:06d}_{snap['reason']}.json")
                tmp = os.path.join(d, name + ".tmp")
                with open(tmp, "w") as f:
                    json.dump(snap, f)
                os.replace(tmp, os.path.join(d, name))
            except OSError:
                pass

    def take_fault(self, tenant: Optional[str] = None) -> Optional[str]:
        """Consume one fault budget entry; returns the armed mode or None.

        With a tenant-scoped fault armed, only requests carrying that
        ``X-PST-Tenant`` value consume budget and fault — other tenants'
        traffic passes untouched (the flood-isolation chaos contract)."""
        if self.fail_mode is None or self.fail_count == 0:
            return None
        if self.fail_tenant is not None and tenant != self.fail_tenant:
            return None
        mode = self.fail_mode
        if self.fail_count > 0:
            self.fail_count -= 1
            if self.fail_count == 0:
                self.fail_mode = None
        self.num_faulted += 1
        return mode


class ChipSim:
    """Opt-in chip contention model (--chip-ms-per-ktok; bench's disagg
    phase), shaped like a continuously-batched serving chip:

    - a PREFILL is one **exclusive** slice — it stalls the running decode
      batch for its whole duration (the ITL hiccup / TTFT head-of-line
      interference fused engines suffer);
    - DECODE bursts are **shared** — all running streams burst
      concurrently (continuous batching), but no burst may start while a
      prefill runs or waits, and a prefill waits for in-flight bursts to
      drain (≤ one burst residual).

    Disaggregation removes exactly the cross-class interference this
    models: a prefill-pool chip never stalls on decode bursts, a
    decode-pool chip only pays tail-compute slices.
    """

    # Prefill slowdown per concurrently-decoding stream: a fused chip's
    # prefill competes with the running decode batch for compute/HBM
    # bandwidth — dedicated prefill chips escape exactly this factor.
    DECODE_DRAG = 0.35

    def __init__(self):
        self._cond = asyncio.Condition()
        self._prefill_active = False
        self._prefill_waiting = 0
        self._decode_bursts = 0
        self.decode_streams = 0

    def enter_decode(self) -> None:
        self.decode_streams += 1

    def exit_decode(self) -> None:
        self.decode_streams = max(self.decode_streams - 1, 0)

    def prefill_drag(self) -> float:
        """How much slower a prefill runs with the current decode batch
        resident on this chip."""
        return 1.0 + self.DECODE_DRAG * self.decode_streams

    async def acquire_prefill(self) -> None:
        async with self._cond:
            self._prefill_waiting += 1
            while self._prefill_active or self._decode_bursts:
                await self._cond.wait()
            self._prefill_waiting -= 1
            self._prefill_active = True

    async def release_prefill(self) -> None:
        async with self._cond:
            self._prefill_active = False
            self._cond.notify_all()

    async def prefill_slice(self, duration: float) -> None:
        await self.acquire_prefill()
        try:
            await asyncio.sleep(max(duration, 0.0) * self.prefill_drag())
        finally:
            await self.release_prefill()

    async def decode_burst(self, duration: float) -> None:
        async with self._cond:
            while self._prefill_active or self._prefill_waiting:
                await self._cond.wait()
            self._decode_bursts += 1
        try:
            await asyncio.sleep(max(duration, 0.0))
        finally:
            async with self._cond:
                self._decode_bursts -= 1
                self._cond.notify_all()


def _prompt_text(body: dict) -> str:
    """Flatten the request prompt (chat messages or completions prompt)
    into one text blob — the fake model's whole world view."""
    if "messages" in body:
        parts = []
        for m in body.get("messages") or []:
            c = m.get("content", "")
            if isinstance(c, str):
                parts.append(c)
        return "\n".join(parts)
    prompt = body.get("prompt", "")
    if isinstance(prompt, list):
        return "\n".join(str(p) for p in prompt)
    return str(prompt)


def _models_payload(state: FakeEngineState) -> dict:
    data = [
        {
            "id": state.model,
            "object": "model",
            "created": int(time.time()),
            "owned_by": "fake",
            "parent": None,
            "root": None,
        }
    ]
    for adapter in state.lora_adapters:
        data.append(
            {
                "id": adapter,
                "object": "model",
                "created": int(time.time()),
                "owned_by": "fake",
                "parent": state.model,
                "root": None,
            }
        )
    return {"object": "list", "data": data}


def create_fake_engine_app(
    model: str = "fake/model",
    speed: float = 500.0,
    ttft: float = 0.0,
    name: str = "",
    ready_delay: float = 0.0,
    warmup_cache_dir: Optional[str] = None,
    kv_capacity_tokens: int = 20000,
    kv_url: Optional[str] = None,
    kv_replication: int = 2,
) -> web.Application:
    state = FakeEngineState(model, speed, kv_capacity_tokens=kv_capacity_tokens,
                            kv_url=kv_url, kv_replication=kv_replication)
    # Instance identity for routing-distribution e2e assertions: surfaces in
    # the X-Served-By header of every generation response.
    state.name = name or f"fake-{uuid.uuid4().hex[:6]}"
    state.configure_warmup(ready_delay, warmup_cache_dir)
    app = web.Application()
    app["state"] = state
    # One simulated chip per engine for the opt-in contention model
    # (state.chip_ms_per_ktok; bench's disagg phase).
    app["chip"] = ChipSim()

    def _kv_session() -> aiohttp.ClientSession:
        sess = app.get("kv_session")
        if sess is None or sess.closed:
            sess = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=10)
            )
            app["kv_session"] = sess
        return sess

    async def _close_kv_session(app_: web.Application) -> None:
        sess = app_.get("kv_session")
        if sess is not None and not sess.closed:
            await sess.close()

    app.on_cleanup.append(_close_kv_session)

    async def _kv_post_manifest(rid: str, payload: dict) -> bool:
        """Replicate a manifest append/marker to the request id's owner
        set; True when at least one owner acked (the survivors' view is
        what the consumer's owner-walk reads)."""
        ok = False
        for url in state.kv_owners(rid):
            try:
                async with _kv_session().post(
                    f"{url}/manifests/{rid}", json=payload
                ) as r:
                    r.raise_for_status()
                ok = True
            except (aiohttp.ClientError, OSError):
                continue
        return ok

    async def _kv_put_pages(
        pages: List[tuple], urls: Optional[List[str]] = None
    ) -> set:
        """Fan ``(hash, payload)`` pages to each page's ring owners (or an
        explicit url list); returns the hashes stored on >= 1 shard."""
        from ..kvserver.server import pack_blocks

        sess = _kv_session()
        by_owner: dict = {}
        for h, data in pages:
            for url in (urls if urls is not None else state.kv_owners(h)):
                by_owner.setdefault(url, []).append((h, data))
        stored: set = set()
        for url, group in by_owner.items():
            try:
                async with sess.post(
                    f"{url}/blocks", data=pack_blocks(group)
                ) as r:
                    r.raise_for_status()
                stored.update(h for h, _ in group)
            except (aiohttp.ClientError, OSError):
                continue
        return stored

    async def _kv_publish(rid: str, hashes: List[int], faulted: bool,
                          chunk_delay: Optional[float] = None) -> None:
        """Producer leg: publish deterministic pages + manifest appends in
        ``kv_publish_chunks`` batches with a delay between them — the
        simulated chunked prefill the decode side overlaps against. Pages
        fan to their R ring owners, so a single shard SIGKILLed
        mid-handoff leaves the transfer intact (the degradation matrix).
        A ``transfer`` fault (or a wholly-dead kvserver tier) publishes
        nothing, so the manifest never completes and the consumer times
        out into its fused fallback."""
        n = max(state.kv_publish_chunks, 1)
        per = max(-(-len(hashes) // n), 1)
        for i in range(0, len(hashes), per):
            chunk = hashes[i : i + per]
            if not faulted:
                stored = await _kv_put_pages(
                    [(h, f"page-{h}".encode()) for h in chunk]
                )
                ok = stored >= set(chunk)
                if ok:
                    ok = await _kv_post_manifest(rid, {"hashes": chunk})
                if ok:
                    state.kv_published_blocks += len(chunk)
                else:
                    faulted = True  # every owner of some page is dead
            await asyncio.sleep(
                state.kv_chunk_delay if chunk_delay is None else chunk_delay
            )
        if faulted:
            state.kv_transfer_fallbacks += 1
            return
        if not await _kv_post_manifest(
            rid, {"complete": True, "total_blocks": len(hashes)}
        ):
            state.kv_transfer_fallbacks += 1

    async def _kv_fetch_blocks(hashes: List[int]) -> int:
        """Batch-fetch blocks with per-hash ring-walk failover, integrity
        verification, quarantine-on-corrupt and read-repair — the fake
        twin of ShardedKVClient.get_blocks. Returns the number of VERIFIED
        blocks fetched; a corrupt copy is quarantined on its shard and the
        walk falls over to the next replica, never counting the bad copy."""
        from ..kvserver.server import unpack_blocks

        sess = _kv_session()
        groups: dict = {}
        for h in hashes:
            groups.setdefault(tuple(state.kv_walk(h)), []).append(h)
        fetched = 0
        repairs: dict = {}  # owner url -> [(hash, payload)]
        for walk, group in groups.items():
            owner_set = {h: set(state.kv_owners(h)) for h in group}
            remaining = list(group)
            missed: dict = {h: [] for h in group}
            for url in walk:
                if not remaining:
                    break
                got: dict = {}
                try:
                    async with sess.get(
                        f"{url}/blocks",
                        params={"hashes": ",".join(
                            str(h) for h in remaining
                        )},
                    ) as r:
                        if r.status == 200:
                            corrupt: List[int] = []
                            for h, data in unpack_blocks(
                                await r.read(), corrupt=corrupt
                            ):
                                got[h] = data
                            if corrupt:
                                state.kv_integrity_failures += len(corrupt)
                                try:
                                    async with sess.post(
                                        f"{url}/admin/quarantine",
                                        json={"hashes": corrupt},
                                    ):
                                        pass
                                except (aiohttp.ClientError, OSError):
                                    pass
                except (aiohttp.ClientError, OSError, ValueError):
                    pass
                still = []
                for h in remaining:
                    if h in got:
                        fetched += 1
                        for owner in missed[h]:
                            repairs.setdefault(owner, []).append(
                                (h, got[h])
                            )
                        continue
                    if url in owner_set[h]:
                        missed[h].append(url)
                    still.append(h)
                remaining = still
        for url, pages in repairs.items():
            stored = await _kv_put_pages(pages, urls=[url])
            state.kv_read_repairs += len(stored)
        return fetched

    async def _kv_prefetch(rid: str, faulted: bool) -> dict:
        """Consumer leg: follow the manifest (long-poll) and batch-fetch
        published blocks until the completion marker — the real handoff
        protocol. Timeout/fault → fused fallback (serve anyway)."""
        expire = time.monotonic() + state.kv_transfer_timeout
        have = 0
        fetched = 0
        complete = False
        while not faulted and time.monotonic() < expire:
            remaining = expire - time.monotonic()
            view = None
            sess = _kv_session()
            # Owner-walk manifest read: the first healthy owner carries
            # the long-poll, later owners get a quick check — a replica
            # that missed appends cannot stall the consumer.
            wait = round(min(remaining, 0.5), 3)
            for url in state.kv_owners(rid):
                try:
                    async with sess.get(
                        f"{url}/manifests/{rid}",
                        params={"wait_s": wait, "have": have},
                    ) as r:
                        state.manifest_fetches += 1
                        wait = 0
                        if r.status == 200:
                            view = await r.json()
                            break
                except (aiohttp.ClientError, OSError):
                    continue
            if view is None:
                await asyncio.sleep(0.02)
                continue
            try:
                new = (view.get("hashes") or [])[have:]
                if new:
                    fetched += await _kv_fetch_blocks(new)
                have = len(view.get("hashes") or [])
                if view.get("complete") and have >= int(
                    view.get("total_blocks") or 0
                ):
                    complete = True
                    break
            except (aiohttp.ClientError, OSError, ValueError):
                await asyncio.sleep(0.05)
        state.kv_prefetched_blocks += fetched
        if not complete:
            state.kv_transfer_fallbacks += 1
        return {"complete": complete, "blocks": fetched}

    async def list_models(request: web.Request) -> web.Response:
        return web.json_response(_models_payload(state))

    def _deadline_budget_s(request: web.Request) -> Optional[float]:
        """Remaining budget (seconds) from X-PST-Deadline-Ms, or None."""
        raw = request.headers.get("X-PST-Deadline-Ms")
        if raw is None:
            return None
        try:
            return float(raw) / 1000.0
        except ValueError:
            return None

    def _echo_trace_headers(request: web.Request) -> dict:
        """Echo the received trace headers back so e2e tests can assert
        propagation on every leg — including retries, hedges, and
        drain/shed rejections — without engine-side state."""
        out = {}
        tp = request.headers.get("traceparent")
        rid = request.headers.get("X-Request-Id")
        if tp is not None:
            out["X-Echo-Traceparent"] = tp
        if rid is not None:
            out["X-Echo-Request-Id"] = rid
        return out

    def _deadline_exceeded_response(request: web.Request) -> web.Response:
        return web.json_response(
            {"error": {"message": "deadline exceeded",
                       "type": "deadline_exceeded", "code": 504}},
            status=504,
            headers={"X-PST-Deadline-Exceeded": "1",
                     "X-Served-By": state.name,
                     **_echo_trace_headers(request)},
        )

    async def _generate(request: web.Request, is_chat: bool) -> web.StreamResponse:
        # Structured-log correlation (--log-format json): the router's
        # propagated trace/request ids land on this engine's log lines
        # and on its stage-histogram exemplars, so e2e legs can join
        # router logs, engine logs, exemplars and /debug/requests on one
        # trace id — same contract as the real engine server. The token
        # is released on EVERY exit path (shed/drain/warming/fault
        # included): aiohttp serves keep-alive requests sequentially in
        # one connection context, and a leaked binding would stamp the
        # NEXT request's log lines with this request's identity.
        parsed_tp = parse_traceparent(request.headers.get("traceparent"))
        trace_id = parsed_tp[0] if parsed_tp else None
        log_token = bind_log_context(
            request_id=request.headers.get("X-Request-Id"),
            trace_id=trace_id,
            tenant=request.headers.get("X-PST-Tenant"),
        )
        try:
            return await _generate_correlated(request, is_chat, trace_id)
        finally:
            unbind_log_context(log_token)

    async def _generate_correlated(
        request: web.Request, is_chat: bool, trace_id
    ) -> web.StreamResponse:
        body = await request.json()
        state.requests_seen.append(body)
        budget = _deadline_budget_s(request)
        state.deadlines_seen.append(request.headers.get("X-PST-Deadline-Ms"))
        state.traces_seen.append({
            "traceparent": request.headers.get("traceparent"),
            "request_id": request.headers.get("X-Request-Id"),
        })
        tenant = request.headers.get("X-PST-Tenant")
        state.tenants_seen.append({
            "tenant": tenant,
            "tenant_class": request.headers.get("X-PST-Tenant-Class"),
        })
        echo = _echo_trace_headers(request)
        t_admission = time.monotonic()
        if budget is not None and budget <= 0:
            # The real engine sheds already-expired work at admission; a
            # router honoring the contract never forwards such a request.
            return _deadline_exceeded_response(request)
        if state.sleeping:
            # Parity with the real engine's sleep gate: a slept engine
            # refuses generation outright. The tagged 503 lets the router
            # fail over (and fire a wake) without feeding the breaker.
            return web.json_response(
                {"error": {"message": "engine is sleeping",
                           "type": "service_unavailable", "code": 503}},
                status=503,
                headers={"X-PST-Sleeping": "1", **echo},
            )
        if state.draining:
            return web.json_response(
                {"error": {"message": "engine is draining",
                           "type": "service_unavailable", "code": 503}},
                status=503,
                headers={"X-PST-Draining": "1", **echo},
            )
        if state.warming:
            # Same tagged-503 contract as the real engine's warming gate:
            # the router marks the endpoint warming and fails over without
            # feeding the breaker.
            return web.json_response(
                {"error": {"message": "engine is warming up (precompiling)",
                           "type": "service_unavailable", "code": 503}},
                status=503,
                headers={"X-PST-Warming": "1", **echo},
            )
        fault = state.take_fault(tenant)
        if fault == "slow":
            delay = state.fail_delay
            if state.fail_jitter:
                delay += random.uniform(0.0, state.fail_jitter)
            if budget is not None and delay >= budget:
                # The injected latency blows the budget: honor the deadline
                # — sleep until it expires, then 504 (what a deadline-
                # shedding engine does when a sequence expires mid-decode).
                await asyncio.sleep(max(budget, 0.0))
                return _deadline_exceeded_response(request)
            await asyncio.sleep(delay)
            # ... then serve normally below (slow, not broken).
        if fault == "error":
            return web.json_response(
                {"error": {"message": "injected failure",
                           "type": "internal_error",
                           "code": state.fail_status}},
                status=state.fail_status,
                headers=echo,
            )
        if fault == "hang":
            # Hold the request open until the caller gives up (poll the
            # transport instead of one long sleep so server shutdown isn't
            # blocked behind a still-running handler).
            while request.transport is not None and not request.transport.is_closing():
                await asyncio.sleep(0.1)
            return web.Response(status=500)
        n_tokens = int(body.get("max_tokens") or state.max_tokens_default)
        stream = bool(body.get("stream", False))
        die_midstream = fault == "midstream"
        # Disagg KV handoff (docs/disagg.md): the router's two-leg flow
        # stamps kv_transfer_params; with a kvserver configured this fake
        # speaks the real manifest protocol. A `transfer` fault breaks
        # ONLY the handoff (fused fallback, no client-visible error).
        kv_params = body.get("kv_transfer_params")
        kv_params = kv_params if isinstance(kv_params, dict) else {}
        kv_rid = kv_params.get("request_id")
        kv_role = kv_params.get("role")
        transfer_fault = fault == "transfer"
        state.num_running += 1
        req_id = f"fake-{uuid.uuid4().hex[:12]}"
        token_interval = 1.0 / state.speed if state.speed > 0 else 0.0
        # Deterministic *continuation* semantics: the fake model's output
        # is "tokN tokN+1 ..." where N counts the tokNs already present in
        # the prompt — so a resume request carrying generated-so-far text
        # continues exactly where an unbroken run would have, like a
        # temperature-0 model continuing its own output.
        prompt_text = _prompt_text(body)
        state.account_prefix(prompt_text)
        tok_start = len(re.findall(r"tok\d+", prompt_text))
        # The fake "tokenizer": every generated tokN is one token (even
        # when a continuation glued it to the prompt tail without a
        # space), every other whitespace word is one token — so a
        # continuation request's prompt_tokens equals the original
        # prompt's plus the tokens already generated.
        prompt_tokens = max(
            tok_start + len(re.sub(r"tok\d+", " ", prompt_text).split()), 1
        )
        include_usage = bool(
            (body.get("stream_options") or {}).get("include_usage")
        )
        # Deterministic cost attribution + flight records (the real
        # engine's contract; docs/observability.md). The fake knows its
        # whole output upfront, so streams carry the header too.
        cost = state.fake_cost(prompt_tokens, n_tokens)
        cost_header = {"X-PST-Cost": json.dumps(cost, separators=(",", ":"))}
        state.record_flight(prompt_tokens, n_tokens)
        if fault == "stall":
            # One-shot N-ms stall on this generation's decode step (the
            # BENCH_r05 signature, inducible on CPU): the request serves
            # normally but pays fail_delay seconds first, and the flight
            # ring retains a deterministic tail_outlier snapshot naming
            # the stalled bucket and queue depths.
            await asyncio.sleep(max(state.fail_delay, 0.0))
            state.record_stall(state.fail_delay, n_tokens)
        created = int(time.time())
        logger.info(
            "generation: model=%s stream=%s tokens=%s",
            body.get("model"), bool(body.get("stream")),
            body.get("max_tokens"),
        )
        chip = request.app.get("chip")
        chip_on = state.chip_ms_per_ktok > 0 and chip is not None
        decode_entered = False
        try:
            # Mirror the real engine's stage decomposition so mixed-workload
            # e2e tests see engine-side pst_stage_duration_seconds labels
            # (with the propagated trace id as the bucket exemplar).
            observe_stage("engine", "engine_admission",
                          time.monotonic() - t_admission,
                          trace_id=trace_id)
            prefetch_complete = False
            if kv_rid and state.kv_url and kv_role == "consumer":
                # Prefetch BEFORE the chip: following the manifest is
                # DCN work, not compute — it overlaps the remote prefill.
                t_fetch = time.monotonic()
                fetch = await _kv_prefetch(str(kv_rid), transfer_fault)
                prefetch_complete = fetch["complete"]
                observe_stage("engine", "kv_prefetch",
                              time.monotonic() - t_fetch, trace_id=trace_id)
            t_prefill = time.monotonic()
            if ttft:
                await asyncio.sleep(ttft)
            prefill_s = 0.0
            if chip_on:
                prefill_s = (prompt_tokens / 1000.0) * (
                    state.chip_ms_per_ktok / 1000.0
                )
                if kv_role == "consumer" and prefetch_complete:
                    prefill_s *= 0.1  # prefix arrived over the wire
            if kv_rid and state.kv_url and kv_role == "producer":
                # The simulated chunked prefill IS the publish loop: each
                # chunk's blocks land on the store before the next chunk
                # "computes", so a concurrently dispatched decode leg
                # observes genuine transfer/prefill overlap. Under the
                # chip model the prefill slice is exclusive and the
                # per-chunk pacing IS the slice (publishing adds no wall
                # beyond the compute it rides).
                if chip_on:
                    # The publisher runs OFF the step thread in the real
                    # engine: the chunk-paced publish overlaps the
                    # exclusive prefill slice instead of inflating it
                    # with DCN round trips.
                    n_chunks = max(state.kv_publish_chunks, 1)
                    pub = asyncio.ensure_future(_kv_publish(
                        str(kv_rid), kv_chunk_hashes(prompt_text),
                        transfer_fault,
                        chunk_delay=prefill_s / n_chunks,
                    ))
                    try:
                        await chip.prefill_slice(prefill_s)
                    finally:
                        await pub
                else:
                    await _kv_publish(
                        str(kv_rid), kv_chunk_hashes(prompt_text),
                        transfer_fault,
                    )
            elif chip_on and prefill_s > 0:
                await chip.prefill_slice(prefill_s)
            observe_stage("engine", "prefill", time.monotonic() - t_prefill,
                          trace_id=trace_id)
            t_decode = time.monotonic()
            decode_count = 0
            if chip_on and n_tokens > 1:
                # This request's decode stream joins the chip's resident
                # batch: every prefill pays the drag while it lives.
                chip.enter_decode()
                decode_entered = True

            async def decode_pace():
                """One token of decode. Under the chip model tokens are
                produced in bursts of 8 holding the chip exclusively —
                the multi-step decode burst that makes an arriving
                prefill wait, i.e. the interference disagg removes."""
                nonlocal decode_count
                if chip_on:
                    if decode_count % 8 == 0:
                        burst = min(8, n_tokens - decode_count)
                        await chip.decode_burst(
                            burst * (token_interval or 0.0005)
                        )
                    decode_count += 1
                elif token_interval:
                    await asyncio.sleep(token_interval)
            if stream:
                resp = web.StreamResponse(status=200)
                resp.headers["Content-Type"] = "text/event-stream"
                resp.headers["X-Served-By"] = state.name
                resp.headers.update(cost_header)
                for k, v in echo.items():
                    resp.headers[k] = v
                await resp.prepare(request)
                for i in range(n_tokens):
                    if die_midstream and i >= state.fail_after_chunks:
                        # Drop the connection at the exact chunk boundary
                        # (0 = before any delta reaches the wire).
                        request.transport.close()
                        return resp
                    final = i == n_tokens - 1
                    finish = "length" if final else None
                    if is_chat:
                        chunk = {
                            "id": req_id,
                            "object": "chat.completion.chunk",
                            "created": created,
                            "model": state.model,
                            "choices": [
                                {
                                    "index": 0,
                                    "delta": {"content": f"tok{tok_start + i} "},
                                    "finish_reason": finish,
                                }
                            ],
                        }
                    else:
                        chunk = {
                            "id": req_id,
                            "object": "text_completion",
                            "created": created,
                            "model": state.model,
                            "choices": [
                                {"index": 0, "text": f"tok{tok_start + i} ",
                                 "finish_reason": finish}
                            ],
                        }
                    # No pst_cost in the streamed usage chunk: the
                    # router's stream journal merges cross-leg usage down
                    # to the three OpenAI fields, so a resumed stream
                    # must byte-match an unfaulted one — the fake's
                    # streaming cost surface is the X-PST-Cost header
                    # (deterministic, so it CAN ride the 200 headers).
                    if final and include_usage:
                        chunk["usage"] = {
                            "prompt_tokens": prompt_tokens,
                            "completion_tokens": n_tokens,
                            "total_tokens": prompt_tokens + n_tokens,
                        }
                    await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())
                    await decode_pace()
                if die_midstream:
                    # fail_after_chunks >= max_tokens: death after the last
                    # delta but before the terminal [DONE].
                    request.transport.close()
                    return resp
                await resp.write(b"data: [DONE]\n\n")
                observe_stage("engine", "decode",
                              time.monotonic() - t_decode,
                              trace_id=trace_id)
                await resp.write_eof()
                return resp
            else:
                if chip_on:
                    for _ in range(n_tokens):
                        await decode_pace()
                elif token_interval:
                    await asyncio.sleep(token_interval * n_tokens)
                text = " ".join(f"tok{tok_start + i}" for i in range(n_tokens))
                usage = {
                    "prompt_tokens": prompt_tokens,
                    "completion_tokens": n_tokens,
                    "total_tokens": prompt_tokens + n_tokens,
                    "pst_cost": cost,
                }
                if is_chat:
                    payload = {
                        "id": req_id,
                        "object": "chat.completion",
                        "created": created,
                        "model": state.model,
                        "choices": [
                            {
                                "index": 0,
                                "message": {"role": "assistant", "content": text},
                                "finish_reason": "length",
                            }
                        ],
                        "usage": usage,
                    }
                else:
                    payload = {
                        "id": req_id,
                        "object": "text_completion",
                        "created": created,
                        "model": state.model,
                        "choices": [
                            {"index": 0, "text": text, "finish_reason": "length"}
                        ],
                        "usage": usage,
                    }
                observe_stage("engine", "decode",
                              time.monotonic() - t_decode,
                              trace_id=trace_id)
                return web.json_response(
                    payload,
                    headers={"X-Served-By": state.name, **cost_header, **echo},
                )
        finally:
            state.num_running -= 1
            if decode_entered:
                chip.exit_decode()

    async def chat(request: web.Request) -> web.StreamResponse:
        return await _generate(request, is_chat=True)

    async def completions(request: web.Request) -> web.StreamResponse:
        return await _generate(request, is_chat=False)

    async def metrics(request: web.Request) -> web.Response:
        hit_rate = state.prefix_hits / state.prefix_queries if state.prefix_queries else 0.0
        text = "\n".join(
            [
                "# TYPE vllm:num_requests_running gauge",
                f"vllm:num_requests_running {state.num_running}",
                "# TYPE vllm:num_requests_waiting gauge",
                f"vllm:num_requests_waiting {state.num_waiting}",
                "# TYPE vllm:gpu_prefix_cache_hit_rate gauge",
                f"vllm:gpu_prefix_cache_hit_rate {hit_rate}",
                "# TYPE vllm:gpu_prefix_cache_hits_total counter",
                f"vllm:gpu_prefix_cache_hits_total {state.prefix_hits}",
                "# TYPE vllm:gpu_prefix_cache_queries_total counter",
                f"vllm:gpu_prefix_cache_queries_total {state.prefix_queries}",
                "# TYPE vllm:gpu_cache_usage_perc gauge",
                f"vllm:gpu_cache_usage_perc {state.kv_occupancy:.4f}",
                # Engine telemetry (docs/observability.md "Engine
                # telemetry"): deterministic values so router-side SLO /
                # scraper e2e tests run hermetically against the fake.
                "# TYPE pst_engine_compile counter",
                'pst_engine_compile_total{kind="prefill",shape_bucket="b1xt128"} 3',
                'pst_engine_compile_total{kind="decode",shape_bucket="b4"} 2',
                "# TYPE pst_engine_compile_seconds histogram",
                'pst_engine_compile_seconds_bucket{kind="prefill",le="+Inf"} 3',
                'pst_engine_compile_seconds_sum{kind="prefill"} 4.5',
                'pst_engine_compile_seconds_count{kind="prefill"} 3',
                "# TYPE pst_engine_step_duration_seconds histogram",
                'pst_engine_step_duration_seconds_bucket{kind="decode",batch_bucket="b4",le="+Inf"} 10',
                'pst_engine_step_duration_seconds_sum{kind="decode",batch_bucket="b4"} 0.5',
                'pst_engine_step_duration_seconds_count{kind="decode",batch_bucket="b4"} 10',
                "# TYPE pst_engine_batch_fill_ratio histogram",
                'pst_engine_batch_fill_ratio_bucket{kind="decode",le="+Inf"} 10',
                'pst_engine_batch_fill_ratio_sum{kind="decode"} 7.5',
                'pst_engine_batch_fill_ratio_count{kind="decode"} 10',
                "# TYPE pst_engine_tokens_per_second gauge",
                'pst_engine_tokens_per_second{kind="decode"} 1234.0',
                "# TYPE pst_engine_mfu gauge",
                "pst_engine_mfu 0.31",
                "# TYPE pst_engine_kv_page_occupancy gauge",
                f"pst_engine_kv_page_occupancy {state.kv_occupancy:.4f}",
                "# TYPE pst_engine_kv_page_high_watermark gauge",
                "pst_engine_kv_page_high_watermark 0.55",
                "# TYPE pst_engine_host_gap_seconds histogram",
                'pst_engine_host_gap_seconds_bucket{batch_bucket="b4",le="0.001"} 5',
                'pst_engine_host_gap_seconds_bucket{batch_bucket="b4",le="0.005"} 8',
                'pst_engine_host_gap_seconds_bucket{batch_bucket="b4",le="+Inf"} 10',
                'pst_engine_host_gap_seconds_sum{batch_bucket="b4"} 0.02',
                'pst_engine_host_gap_seconds_count{batch_bucket="b4"} 10',
                "# TYPE pst_engine_preemptions counter",
                "pst_engine_preemptions_total 1",
                "# TYPE pst_engine_swap_out counter",
                "pst_engine_swap_out_total 2",
                "# TYPE pst_engine_swap_in counter",
                "pst_engine_swap_in_total 2",
                "# TYPE pst_engine_start_time_seconds gauge",
                "pst_engine_start_time_seconds 1700000000.0",
                "# TYPE pst_engine_startup_seconds gauge",
                'pst_engine_startup_seconds{phase="load"} 120.0',
                'pst_engine_startup_seconds{phase="shard"} 15.0',
                'pst_engine_startup_seconds{phase="warmup"} 5.0',
                # Simulated precompile warmup (docs/engine.md "Warmup &
                # precompilation"): phase time tracks the effective ready
                # delay (warm restarts report a strictly smaller value),
                # coverage climbs 0→1 during the delay, and the cache
                # counters are all-misses cold / all-hits warm.
                'pst_engine_startup_seconds{phase="precompile"} '
                f"{state.effective_ready_delay:.3f}",
                "# TYPE pst_engine_warmup_coverage gauge",
                f"pst_engine_warmup_coverage {state.warmup_coverage:.4f}",
                "# TYPE pst_engine_warmup_buckets gauge",
                'pst_engine_warmup_buckets{state="total"} '
                f"{FAKE_WARMUP_BUCKETS}",
                'pst_engine_warmup_buckets{state="compiled"} '
                f"{int(round(state.warmup_coverage * FAKE_WARMUP_BUCKETS))}",
                "# TYPE pst_engine_compile_cache_hits counter",
                "pst_engine_compile_cache_hits_total "
                f"{FAKE_WARMUP_BUCKETS if state.warm_start else 0}",
                "# TYPE pst_engine_compile_cache_misses counter",
                "pst_engine_compile_cache_misses_total "
                f"{0 if state.warm_start else FAKE_WARMUP_BUCKETS}",
                # Streamed disagg handoff (docs/disagg.md) — same pst:
                # names as the real engine server.
                "# TYPE pst:kv_published_blocks counter",
                f"pst:kv_published_blocks_total {state.kv_published_blocks}",
                "# TYPE pst:kv_prefetched_blocks counter",
                f"pst:kv_prefetched_blocks_total {state.kv_prefetched_blocks}",
                "# TYPE pst:kv_transfer_fallbacks counter",
                "pst:kv_transfer_fallbacks_total "
                f"{state.kv_transfer_fallbacks}",
                # Replicated remote tier (docs/kvserver.md) — underscore
                # names, same as the real engines' shared obs registry.
                "# TYPE pst_kv_integrity_failures counter",
                'pst_kv_integrity_failures_total{source="prefetch"} '
                f"{state.kv_integrity_failures}",
                "# TYPE pst_kv_read_repairs counter",
                f"pst_kv_read_repairs_total {state.kv_read_repairs}",
                "",
            ]
        )
        # Same contract as the real engine: pst_stage_duration_seconds
        # rides the shared observability registry.
        text += render_obs_metrics().decode()
        return web.Response(text=text, content_type="text/plain")

    async def debug_profile(request: web.Request) -> web.Response:
        """Same surface as the real engine's POST /debug/profile, always
        the graceful CPU no-op (a fake engine has no device timeline)."""
        body = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:  # noqa: BLE001
                body = {}
        if not isinstance(body, dict):  # e.g. a bare JSON list
            body = {}
        try:
            duration_ms = float(
                body.get("duration_ms")
                or request.query.get("duration_ms", 1000)
            )
        except (TypeError, ValueError):
            return web.json_response(
                {"error": {"message": "duration_ms must be a number",
                           "type": "invalid_request_error", "code": 400}},
                status=400,
            )
        return web.json_response({
            "status": "skipped",
            "reason": "no accelerator backend (fake engine) — nothing to "
                      "profile",
            "duration_ms": duration_ms,
        })

    async def debug_state(request: web.Request) -> web.Response:
        """Deterministic engine introspection (docs/observability.md
        "Fleet debugging"): the same KV/tenant/compile numbers the
        /metrics surface exports, as one JSON object — what /debug/fleet
        shows for this engine, straight from the source, so tests can
        cross-validate the gossip-merged snapshot against engine truth."""
        hit_rate = (
            state.prefix_hits / state.prefix_queries
            if state.prefix_queries else 0.0
        )
        return web.json_response({
            "name": state.name,
            "model": state.model,
            # Same conjuncts as the real engine's readiness: sleeping is
            # not ready (a contract test written against the fake must
            # hold against the real engine too).
            "ready": not (state.warming or state.draining or state.sleeping
                          or state.fail_mode == "error"),
            "draining": state.draining,
            "warming": state.warming,
            "sleeping": state.sleeping,
            "sleep_level": state.sleep_level,
            "in_flight": state.num_running,
            "kv_occupancy": round(state.kv_occupancy, 4),
            "kv_capacity_tokens": state.kv_capacity_tokens,
            "cached_tokens": state.kv_tokens,
            "kv_published_blocks": state.kv_published_blocks,
            "kv_prefetched_blocks": state.kv_prefetched_blocks,
            "kv_transfer_fallbacks": state.kv_transfer_fallbacks,
            "kv_read_repairs": state.kv_read_repairs,
            "kv_integrity_failures": state.kv_integrity_failures,
            "kv_shards": len(state.kv_urls),
            "kv_replication": state.kv_replication,
            "manifest_fetches": state.manifest_fetches,
            "prefix_hit_rate": round(hit_rate, 4),
            # Matches the deterministic pst_engine_compile_total samples
            # in /metrics (3 prefill + 2 decode).
            "compiles_total": 5,
            "flight": {
                "capacity": state.flight_capacity,
                "total_steps": state.flight_total,
                "resident": len(state.flight_records),
                "snapshots": 0,
            },
            "tenants_seen": state.tenants_seen[-64:],
            "requests_seen": len(state.requests_seen),
        })

    async def debug_flight(request: web.Request) -> web.Response:
        """Deterministic flight-recorder ring (the real engine's
        GET /debug/flight shape): two records per generation served, so
        router-side capacity/cost tests assert exact contents without a
        TPU. Supports the same ``?n=`` / ``?window_s=`` filters."""
        records = list(state.flight_records)
        try:
            if "window_s" in request.query:
                cutoff = time.time() - float(request.query["window_s"])
                records = [r for r in records if r["ts"] >= cutoff]
            if "n" in request.query:
                n = int(request.query["n"])
                if n > 0:
                    records = records[-n:]
        except (TypeError, ValueError):
            return web.json_response(
                {"error": {"message": "n and window_s must be numbers",
                           "type": "invalid_request_error", "code": 400}},
                status=400,
            )
        return web.json_response({
            "capacity": state.flight_capacity,
            "total_steps": state.flight_total,
            "resident": len(state.flight_records),
            "fields": [
                "ts", "kind", "bucket", "device_s", "host_gap_s",
                "compiled", "waiting", "running", "swapped",
                "kv_occupancy", "preemptions", "batch_tier_rows", "tokens",
            ],
            "records": records,
            "snapshot_log": list(state.flight_snapshots),
            **(
                {"restored_snapshots": list(state.restored_snapshots),
                 "snapshot_dir": state.flight_snapshot_dir}
                if request.query.get("snapshots") in ("1", "true") else {}
            ),
        })

    async def health(request: web.Request) -> web.Response:
        if state.fail_mode == "error":
            return web.json_response({"status": "failing"}, status=500)
        status = (
            "draining" if state.draining
            else "warming" if state.warming
            else "ok"
        )
        return web.json_response({"status": status})

    async def ready(request: web.Request) -> web.Response:
        """Same contract as the real engine's /ready: 200 once the
        (simulated) precompile pass finished, 503 + reason otherwise."""
        warmup = {
            "mode": "full" if state.ready_delay else "off",
            "buckets_total": FAKE_WARMUP_BUCKETS,
            "buckets_compiled": int(
                round(state.warmup_coverage * FAKE_WARMUP_BUCKETS)
            ),
            "coverage": round(state.warmup_coverage, 4),
            "seconds": round(state.effective_ready_delay, 3),
            "warm_start": state.warm_start,
        }
        if state.fail_mode == "error":
            reason = "unhealthy"
        elif state.sleeping:
            reason = "sleeping"
        elif state.warming:
            reason = "warming"
        elif state.draining:
            reason = "draining"
        else:
            return web.json_response({"ready": True, "warmup": warmup})
        return web.json_response(
            {"ready": False, "reason": reason, "warmup": warmup}, status=503
        )

    async def admin_warmup(request: web.Request) -> web.Response:
        """Re-enter (or reconfigure) the simulated warmup: {"ready_delay":
        seconds, "cache_dir": path|null, "reset_cache": bool}. Lets
        discovery/routing tests flip an engine to warming mid-run without
        restarting the app."""
        body = await request.json() if request.can_read_body else {}
        cache_dir = body.get("cache_dir", state.warmup_cache_dir)
        if body.get("reset_cache") and cache_dir:
            try:
                os.remove(os.path.join(cache_dir, "warm"))
            except OSError:
                pass
        state.configure_warmup(
            float(body.get("ready_delay", state.ready_delay)), cache_dir
        )
        return web.json_response({
            "status": "warming" if state.warming else "ready",
            "warm_start": state.warm_start,
            "effective_ready_delay": state.effective_ready_delay,
        })

    async def is_sleeping(request: web.Request) -> web.Response:
        return web.json_response({"is_sleeping": state.sleeping})

    async def admin_fail(request: web.Request) -> web.Response:
        """Arm fault injection: {"mode": "error"|"hang"|"midstream"|"slow",
        "status": 500, "count": -1, "delay": 0.5, "jitter": 0,
        "fail_after_chunks": 3, "tenant": null}. ``slow`` injects
        ``delay`` (+ uniform jitter up to ``jitter``) seconds of latency
        per generation, honoring a propagated deadline with 504.
        ``midstream`` drops the connection after exactly
        ``fail_after_chunks`` streamed delta chunks (0 = before any
        delta; >= max_tokens = after the last delta but before
        ``[DONE]``) — deterministic chunk boundaries for stream
        resumption tests. ``tenant`` scopes the fault to requests whose
        ``X-PST-Tenant`` equals it (isolation chaos legs fault one
        tenant's traffic while the victim's flows untouched). ``stall``
        one-shots a ``delay``-second pause on the next decode step and
        records a deterministic flight snapshot naming the stalled
        bucket + queue state — the BENCH_r05 tail signature on CPU
        (``count`` defaults to 1 for stall: one outlier, not a slow
        engine)."""
        body = await request.json() if request.can_read_body else {}
        mode = body.get("mode", "error")
        if mode not in ("error", "hang", "midstream", "slow", "transfer", "stall"):
            return web.json_response({"error": f"unknown mode {mode!r}"}, status=400)
        state.fail_mode = mode
        state.fail_status = int(body.get("status", 500))
        state.fail_count = int(body.get("count", 1 if mode == "stall" else -1))
        state.fail_delay = float(body.get("delay", 0.5))
        state.fail_jitter = float(body.get("jitter", 0.0))
        state.fail_after_chunks = int(body.get("fail_after_chunks", 3))
        tenant = body.get("tenant")
        state.fail_tenant = str(tenant) if tenant is not None else None
        return web.json_response(
            {"status": "armed", "mode": mode, "tenant": state.fail_tenant}
        )

    async def admin_heal(request: web.Request) -> web.Response:
        state.fail_mode = None
        state.fail_count = -1
        state.fail_tenant = None
        return web.json_response({"status": "healed", "faulted": state.num_faulted})

    async def admin_fill_kv(request: web.Request) -> web.Response:
        """Pin the reported KV occupancy for headroom-spill tests:
        {"occupancy": 0.9} floors the derived occupancy at 0.9;
        {"clear": true} drops the floor AND the simulated cache;
        {"capacity_tokens": N} resizes the simulated KV."""
        body = await request.json() if request.can_read_body else {}
        if not isinstance(body, dict):
            body = {}
        if body.get("clear"):
            state.kv_fill_floor = 0.0
            state.kv_chunks.clear()
            state.kv_tokens = 0
        if "capacity_tokens" in body:
            try:
                state.kv_capacity_tokens = max(int(body["capacity_tokens"]), 1)
            except (TypeError, ValueError):
                return web.json_response(
                    {"error": "capacity_tokens must be an int"}, status=400
                )
        if "occupancy" in body:
            try:
                state.kv_fill_floor = float(body["occupancy"])
            except (TypeError, ValueError):
                return web.json_response(
                    {"error": "occupancy must be a number"}, status=400
                )
        return web.json_response({
            "occupancy": state.kv_occupancy,
            "fill_floor": state.kv_fill_floor,
            "cached_tokens": state.kv_tokens,
            "capacity_tokens": state.kv_capacity_tokens,
        })

    async def drain(request: web.Request) -> web.Response:
        state.draining = True
        if request.query.get("wait"):
            deadline = time.time() + float(request.query.get("timeout", "30"))
            while time.time() < deadline and state.num_running > 0:
                await asyncio.sleep(0.05)
        return web.json_response(
            {"status": "draining", "in_flight": state.num_running}
        )

    async def undrain(request: web.Request) -> web.Response:
        state.draining = False
        return web.json_response(
            {"status": "accepting", "in_flight": state.num_running}
        )

    async def is_draining(request: web.Request) -> web.Response:
        return web.json_response(
            {"is_draining": state.draining, "in_flight": state.num_running}
        )

    async def sleep(request: web.Request) -> web.Response:
        level = request.query.get("level", "1")
        state.sleeping = True
        state.sleep_level = level
        return web.json_response({"status": "sleeping", "level": level})

    async def wake_up(request: web.Request) -> web.Response:
        was_sleeping = state.sleeping
        state.sleeping = False
        state.sleep_level = None
        if was_sleeping:
            # Wake re-enters the simulated warmup exactly like a restart:
            # ``--ready-delay`` governs the wake time, and a warm compile
            # cache (marker file present) shrinks it to the warm-restart
            # fraction — zero fresh compiles, scale-to-zero's
            # wake->first-token bound becomes CPU-measurable.
            state.configure_warmup(state.ready_delay, state.warmup_cache_dir)
        return web.json_response({
            "status": "awake",
            "warming": state.warming,
            "effective_ready_delay": round(state.effective_ready_delay, 3),
        })

    async def load_lora(request: web.Request) -> web.Response:
        body = await request.json()
        name = body.get("lora_name")
        if name and name not in state.lora_adapters:
            state.lora_adapters.append(name)
        return web.json_response({"status": "ok"})

    async def unload_lora(request: web.Request) -> web.Response:
        body = await request.json()
        name = body.get("lora_name")
        if name in state.lora_adapters:
            state.lora_adapters.remove(name)
        return web.json_response({"status": "ok"})

    async def tokenize(request: web.Request) -> web.Response:
        body = await request.json()
        text = body.get("prompt") or ""
        tokens = list(text.encode())
        return web.json_response({"tokens": tokens, "count": len(tokens)})

    async def embeddings(request: web.Request) -> web.Response:
        """Deterministic 64-dim embeddings (the real engine serves model
        embeddings via its encode path; same text → same vector is what
        router-side consumers like the semantic cache need from a fake)."""
        import xxhash

        body = await request.json()
        inputs = body.get("input")
        if isinstance(inputs, str):
            inputs = [inputs]
        data = []
        for i, text in enumerate(inputs or []):
            raw = [
                (xxhash.xxh32_intdigest(f"{text}\x00{j}") % 2001) / 1000.0 - 1.0
                for j in range(64)
            ]
            norm = sum(v * v for v in raw) ** 0.5 or 1.0
            data.append({
                "object": "embedding",
                "index": i,
                "embedding": [v / norm for v in raw],
            })
        return web.json_response({
            "object": "list",
            "data": data,
            "model": body.get("model", state.model),
            "usage": {"prompt_tokens": 0, "total_tokens": 0},
        })

    app.router.add_get("/v1/models", list_models)
    app.router.add_post("/v1/embeddings", embeddings)
    app.router.add_post("/v1/chat/completions", chat)
    app.router.add_post("/v1/completions", completions)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/state", debug_state)
    app.router.add_get("/debug/flight", debug_flight)
    app.router.add_post("/debug/profile", debug_profile)
    app.router.add_get("/health", health)
    app.router.add_get("/ready", ready)
    app.router.add_get("/is_sleeping", is_sleeping)
    app.router.add_post("/sleep", sleep)
    app.router.add_post("/wake_up", wake_up)
    app.router.add_post("/admin/fail", admin_fail)
    app.router.add_post("/admin/heal", admin_heal)
    app.router.add_post("/admin/fill_kv", admin_fill_kv)
    app.router.add_post("/admin/warmup", admin_warmup)
    app.router.add_post("/drain", drain)
    app.router.add_post("/undrain", undrain)
    app.router.add_get("/is_draining", is_draining)
    app.router.add_post("/v1/load_lora_adapter", load_lora)
    app.router.add_post("/v1/unload_lora_adapter", unload_lora)
    app.router.add_post("/tokenize", tokenize)
    return app


def main(argv: Optional[list] = None) -> None:
    p = argparse.ArgumentParser(description="fake TPU serving engine")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9101)
    p.add_argument("--model", default="fake/model")
    p.add_argument("--speed", type=float, default=500.0, help="tokens/sec")
    p.add_argument("--ttft", type=float, default=0.0, help="artificial TTFT (s)")
    p.add_argument("--name", default="", help="instance id (X-Served-By header)")
    p.add_argument("--ready-delay", type=float, default=0.0,
                   help="simulated warmup: /ready reports warming for this "
                        "many seconds after start")
    p.add_argument("--warmup-cache-dir", default=None,
                   help="simulated persistent compile cache: a marker left "
                        "by a previous instance makes this start warm "
                        "(shorter ready delay, all cache hits)")
    p.add_argument("--chip-ms-per-ktok", type=float, default=0.0,
                   help="opt-in chip queueing model: one FIFO chip per "
                        "engine; a prefill is one exclusive slice of this "
                        "many ms per 1000 prompt tokens, each decode "
                        "token a small slice — models the prefill/decode "
                        "head-of-line interference disagg removes "
                        "(bench disagg phase; 0 = off)")
    p.add_argument("--kv-url", default=None,
                   help="remote KV block store (kvserver) base URL: "
                        "enables the disagg handoff protocol — producer "
                        "legs publish deterministic block manifests per "
                        "simulated prefill chunk, consumer legs follow "
                        "them and batch-fetch before decoding; a comma-"
                        "separated list enables the sharded ring client "
                        "(placement, replication, read-repair)")
    p.add_argument("--kv-replication", type=int, default=2,
                   help="replicas per block/manifest on the kvserver "
                        "ring (clamped to the shard count)")
    p.add_argument("--kv-capacity-tokens", type=int, default=20000,
                   help="simulated KV capacity: occupancy and prefix-hit "
                        "eviction derive from it (small values make "
                        "cache-pressure effects visible in tests)")
    p.add_argument("--flight-snapshot-dir", default=None,
                   help="persist flight snapshots (stall outliers) as "
                        "JSON files here, same naming contract as the "
                        "real engine's --flight-snapshot-dir — the "
                        "post-mortem forensics path: bundles survive "
                        "SIGKILL; any snapshots already in the dir are "
                        "loaded back and served via "
                        "/debug/flight?snapshots=1")
    p.add_argument("--log-format", choices=["text", "json"], default="text",
                   help="'json' emits structured log lines enriched with "
                        "the propagated trace/request/tenant ids (same "
                        "contract as the real engine server)")
    args = p.parse_args(argv)
    configure_logging(
        args.log_format, component="engine",
        engine_id=args.name or f"fake:{args.port}",
    )
    app = create_fake_engine_app(
        args.model, args.speed, args.ttft, args.name,
        ready_delay=args.ready_delay, warmup_cache_dir=args.warmup_cache_dir,
        kv_capacity_tokens=args.kv_capacity_tokens,
        kv_url=args.kv_url,
        kv_replication=args.kv_replication,
    )
    app["state"].chip_ms_per_ktok = max(args.chip_ms_per_ktok, 0.0)
    if args.flight_snapshot_dir:
        from ..obs.flight import load_snapshot_dir

        app["state"].flight_snapshot_dir = args.flight_snapshot_dir
        app["state"].restored_snapshots = load_snapshot_dir(
            args.flight_snapshot_dir
        )
    web.run_app(app, host=args.host, port=args.port, access_log=None)


if __name__ == "__main__":
    main()
