"""In-process fake Kubernetes API server (envtest analogue).

Extracted from ``tests/test_operator.py`` so the operator unit tests, the
``operator``-mode e2e legs (``tests/e2e/test_routing.py``) and the bench
autoscale phase all drive the SAME API-server semantics: list/get with
single ``k=v`` label selectors, create/replace with resourceVersion and
generation-bump-on-spec-change, merge-patch of ``/status``, finalizer
deletion semantics, and chunked ``?watch=true`` streams.

The real ``pst-operator`` binary points at :attr:`FakeK8s.url` via
``--api-server``; the router's K8s discovery reaches the same server via
the ``PST_K8S_API_SERVER`` env override — a full closed autoscaling loop
on one CPU host with no cluster.
"""

from __future__ import annotations

import asyncio
import json
import threading

from aiohttp import web

# API path prefixes as the operator addresses them.
PST = "/apis/pst.production-stack.io/v1alpha1"
APPS = "/apis/apps/v1"
CORE = "/api/v1"


class FakeK8s:
    """Minimal namespaced K8s API: enough semantics for the controller."""

    def __init__(self):
        # (api_prefix, plural) -> {name: obj}
        self.store = {}
        self.rv = 0
        self.url = None
        self._ready = threading.Event()
        self._loop = None
        # (prefix, plural) -> list of asyncio.Queue for ?watch=true streams
        self._watchers = {}

    # -- storage helpers --------------------------------------------------

    def bucket(self, prefix, plural):
        return self.store.setdefault((prefix, plural), {})

    def seed(self, prefix, plural, obj):
        name = obj["metadata"]["name"]
        obj["metadata"].setdefault("uid", f"uid-{name}")
        self.bucket(prefix, plural)[name] = obj
        # Seeding after start() is the harness playing kubelet (e.g. the
        # autoscale e2e starting the pods a scaled-up Deployment implies):
        # live ?watch=true streams must see the object appear. Queues are
        # loop-owned, so hop onto the server loop.
        if self._loop is not None and self._loop.is_running():
            self._loop.call_soon_threadsafe(
                self._broadcast, prefix, plural, "ADDED", obj
            )

    def seed_engine_pod(self, name, port, model="base", ip="127.0.0.1"):
        """A Running engine pod as the engine Deployment would produce it."""
        self.seed(CORE, "pods", {
            "metadata": {"name": name, "namespace": "default",
                         "labels": {"model": model}},
            "spec": {"containers": [{
                "name": "engine",
                "ports": [{"containerPort": port}],
            }]},
            "status": {"podIP": ip, "phase": "Running",
                       # The router's pod-IP watcher requires the Ready
                       # condition, not just the phase.
                       "conditions": [{"type": "Ready", "status": "True"}]},
        })

    def seed_router_replica(self, name, port, ip="127.0.0.1"):
        """A router Service + Running pod pair the operator's autoscale
        actuator discovers (component=router Service -> selector -> pod)."""
        self.seed(CORE, "services", {
            "metadata": {
                "name": name, "namespace": "default",
                "labels": {"app.kubernetes.io/component": "router"},
            },
            "spec": {"selector": {"app": name},
                     "ports": [{"port": 80, "targetPort": port}]},
        })
        self.seed(CORE, "pods", {
            "metadata": {"name": f"{name}-0", "namespace": "default",
                         "labels": {"app": name}},
            "spec": {"containers": [{
                "name": "router",
                "ports": [{"containerPort": port}],
            }]},
            "status": {"podIP": ip, "phase": "Running"},
        })

    def _broadcast(self, prefix, plural, event_type, obj):
        for q in self._watchers.get((prefix, plural), []):
            q.put_nowait({"type": event_type, "object": obj})

    # -- aiohttp app ------------------------------------------------------

    def make_app(self):
        app = web.Application()
        app.router.add_route("*", "/{api:apis?}/{rest:.*}", self.handle)
        return app

    async def handle(self, request: web.Request):
        # Paths: /api/v1/namespaces/{ns}/{plural}[/{name}[/status]]
        #        /apis/{group}/{ver}/namespaces/{ns}/{plural}[/{name}[/status]]
        parts = request.path.strip("/").split("/")
        if parts[0] == "api":
            prefix = "/api/" + parts[1]
            rest = parts[2:]
        else:
            prefix = "/apis/" + parts[1] + "/" + parts[2]
            rest = parts[3:]
        if len(rest) < 2 or rest[0] != "namespaces":
            return web.json_response({"error": "bad path"}, status=400)
        plural = rest[2]
        name = rest[3] if len(rest) > 3 else None
        subresource = rest[4] if len(rest) > 4 else None
        bucket = self.bucket(prefix, plural)

        if request.method == "GET" and name is None:
            if request.query.get("watch") == "true":
                # K8s watch wire format: one JSON event object per line,
                # chunked. Synthetic ADDED events for existing objects first
                # (a watch without resourceVersion), then live mutations.
                resp = web.StreamResponse()
                resp.enable_chunked_encoding()
                await resp.prepare(request)
                q = asyncio.Queue()
                for obj in bucket.values():
                    q.put_nowait({"type": "ADDED", "object": obj})
                self._watchers.setdefault((prefix, plural), []).append(q)
                try:
                    while True:
                        event = await q.get()
                        if event is None:  # shutdown sentinel: clean EOF
                            break
                        await resp.write(
                            (json.dumps(event) + "\n").encode()
                        )
                except (ConnectionResetError, asyncio.CancelledError):
                    pass
                finally:
                    self._watchers[(prefix, plural)].remove(q)
                return resp
            items = list(bucket.values())
            selector = request.query.get("labelSelector")
            if selector:
                k, _, v = selector.partition("=")
                items = [
                    o for o in items
                    if o.get("metadata", {}).get("labels", {}).get(k) == v
                ]
            return web.json_response({"kind": "List", "items": items})
        if request.method == "GET":
            if name not in bucket:
                return web.json_response({"error": "not found"}, status=404)
            return web.json_response(bucket[name])
        if request.method == "POST":
            obj = await request.json()
            self.rv += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            obj["metadata"].setdefault("uid", f"uid-{obj['metadata']['name']}")
            obj["metadata"].setdefault("generation", 1)
            bucket[obj["metadata"]["name"]] = obj
            self._broadcast(prefix, plural, "ADDED", obj)
            return web.json_response(obj, status=201)
        if request.method == "PUT":
            obj = await request.json()
            self.rv += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            meta = obj["metadata"]
            # generation bumps only on spec changes (API-server semantics —
            # the operator's watch filter depends on this).
            old = bucket.get(name, {})
            gen = old.get("metadata", {}).get("generation", 1)
            meta["generation"] = (
                gen + 1 if obj.get("spec") != old.get("spec") else gen
            )
            # API-server finalizer semantics: removing the last finalizer
            # from an object marked for deletion actually deletes it.
            if meta.get("deletionTimestamp") and not meta.get("finalizers"):
                bucket.pop(name, None)
                self._broadcast(prefix, plural, "DELETED", obj)
                return web.json_response(obj)
            bucket[name] = obj
            self._broadcast(prefix, plural, "MODIFIED", obj)
            return web.json_response(obj)
        if request.method == "PATCH":
            if name not in bucket:
                return web.json_response({"error": "not found"}, status=404)
            patch = await request.json()
            target = bucket[name]
            if subresource == "status" or "status" in patch:
                target.setdefault("status", {}).update(patch.get("status", {}))
            return web.json_response(target)
        if request.method == "DELETE":
            obj = bucket.get(name)
            if obj and obj.get("metadata", {}).get("finalizers"):
                # Finalizers pending: mark for deletion, keep the object.
                obj["metadata"]["deletionTimestamp"] = "2026-01-01T00:00:00Z"
                self._broadcast(prefix, plural, "MODIFIED", obj)
                return web.json_response(obj)
            bucket.pop(name, None)
            if obj:
                self._broadcast(prefix, plural, "DELETED", obj)
            return web.json_response({"status": "ok"})
        return web.json_response({"error": "unsupported"}, status=405)

    # -- lifecycle --------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        assert self._ready.wait(10)
        return self

    def _run(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def boot():
            self._runner = web.AppRunner(self.make_app())
            await self._runner.setup()
            site = web.TCPSite(self._runner, "127.0.0.1", 0)
            await site.start()
            self.url = f"http://127.0.0.1:{site._server.sockets[0].getsockname()[1]}"
            self._ready.set()

        self._loop.run_until_complete(boot())
        self._loop.run_forever()

    def stop(self):
        """Graceful teardown: end watch streams with a sentinel (clean EOF
        to the operator, no mid-write ConnectionResets), clean the runner
        up on its own loop, then stop the loop. Keeps teardown log noise
        from burying real failures (VERDICT r3 #10; envtest's clean
        lifecycle is the model, suite_test.go:1-88)."""
        if not self._loop:
            return

        async def shutdown():
            for qs in self._watchers.values():
                for q in list(qs):
                    q.put_nowait(None)
            await asyncio.sleep(0.05)  # let handlers write EOF and return
            if getattr(self, "_runner", None) is not None:
                await self._runner.cleanup()
            self._loop.stop()

        asyncio.run_coroutine_threadsafe(shutdown(), self._loop)
        if self._thread is not None:
            self._thread.join(timeout=5)
