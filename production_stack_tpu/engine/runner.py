"""Model runner: marshals scheduler output into jitted device steps.

Owns the device state (sharded params + KV page arrays) and the compiled
step functions. XLA's static-shape world meets continuous batching here:
every step is padded into power-of-two buckets — decode batch width, prefill
chunk length, block-table width — so the number of distinct compilations is
O(log² shapes), all cached by ``jax.jit``. Padding rows write to a
guaranteed-dropped slot (flat index ``nb*bs``) and are masked in attention by
``kv_len = 0``.

Sampling runs inside the same jit (logits never leave the device); only the
``[B]`` sampled token ids are transferred back.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence as Seq

import jax
import jax.numpy as jnp
import numpy as np
import xxhash
from jax.numpy import asarray as jnp_asarray
from jax.sharding import NamedSharding, PartitionSpec as P

from ..logging_utils import init_logger
from ..models.llama import Llama, LlamaConfig, load_hf_params
from ..models.registry import get_model_config
from ..ops.sampling import apply_penalties, sample_tokens
from ..parallel.mesh import MeshConfig, build_mesh
from .config import EngineConfig, resolve_num_kv_blocks
from .scheduler import PrefillItem
from .sequence import Sequence

logger = init_logger(__name__)


def _pow2(n: int, cap: Optional[int] = None) -> int:
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap) if cap else b


# Block tables below this width share one bucket: sequences crossing small
# power-of-two boundaries would otherwise retrace mid-serving, and the pallas
# kernel skips out-of-range pages anyway (only the gather fallback pays for
# the extra width).
_MIN_TABLE_BUCKET = 64


def _seed_for(seq: Sequence) -> int:
    base = (
        seq.sampling.seed
        if seq.sampling.seed is not None
        else xxhash.xxh32(seq.request_id.encode()).intdigest()
    )
    return (base + len(seq.output_token_ids)) & 0x7FFF_FFFF


class ModelRunner:
    def __init__(
        self,
        cfg: EngineConfig,
        model_cfg: Optional[LlamaConfig] = None,
        mesh=None,
    ):
        self.cfg = cfg
        self.model_cfg = model_cfg or get_model_config(cfg.model)
        self.model = Llama(self.model_cfg)
        tp = cfg.tensor_parallel_size
        pp = max(cfg.pipeline_parallel_size, 1)
        self._pp = pp
        if self.model_cfg.num_kv_heads % max(tp, 1):
            raise ValueError(
                f"num_kv_heads={self.model_cfg.num_kv_heads} not divisible by "
                f"tensor_parallel_size={tp}"
            )
        if self.model_cfg.num_layers % pp:
            raise ValueError(
                f"num_layers={self.model_cfg.num_layers} not divisible by "
                f"pipeline_parallel_size={pp}"
            )
        self.mesh = mesh or build_mesh(
            MeshConfig(
                tensor_parallel_size=tp,
                data_parallel_size=cfg.data_parallel_size,
                pipeline_parallel_size=pp,
            )
        )

        t0 = time.time()
        if os.path.isdir(cfg.model):
            params = load_hf_params(self.model_cfg, cfg.model)
        else:
            params = self.model.init_params(jax.random.PRNGKey(cfg.seed))
        pspecs = self.model.param_pspecs(pipeline=pp > 1)
        if cfg.enable_lora:
            params["layers"].update(
                self.model.init_lora_bank(cfg.max_loras, cfg.max_lora_rank)
            )
            pspecs["layers"].update(self.model.lora_pspecs(pipeline=pp > 1))
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            params,
            pspecs,
        )
        leaves = jax.tree.leaves(self.params)
        self.param_count = sum(x.size for x in leaves)
        param_bytes = sum(x.size * x.dtype.itemsize for x in leaves)
        logger.info(
            "params ready: %.2f GiB total, %.1fs", param_bytes / 2**30, time.time() - t0
        )

        self.num_blocks = resolve_num_kv_blocks(
            cfg, self.model_cfg, param_bytes // (max(tp, 1) * pp)
        )
        self.max_table_width = -(-cfg.max_model_len // cfg.block_size)
        cache_sh = NamedSharding(self.mesh, Llama.cache_pspec(pipeline=pp > 1))
        self._dispatch_restore_kv()  # single source of truth for allocation
        self._repl = NamedSharding(self.mesh, P())
        # Decode batches shard rows over dp (independent sequences — the
        # in-engine data-parallel axis); prefill chunks stay replicated.
        self._dp = cfg.data_parallel_size
        self._row = NamedSharding(self.mesh, P("dp"))
        self._drop_slot = self.num_blocks * cfg.block_size

        model = self.model
        attn_impl = cfg.attn_impl
        mesh_for_pp = self.mesh if pp > 1 else None

        def step(params, kv_cache, batch: Dict[str, Any]):
            logits, kv_cache = model.forward(
                params,
                batch["tokens"],
                batch["positions"],
                batch["write_idx"],
                batch["block_tables"],
                batch["kv_lens"],
                batch["last_idx"],
                kv_cache,
                lora_idx=batch.get("lora_idx"),
                lora_scale=batch.get("lora_scale"),
                attn_impl=attn_impl,
                pp_size=pp,
                mesh=mesh_for_pp,
            )
            if "penalty_prompt" in batch:
                logits = apply_penalties(
                    logits,
                    batch["penalty_prompt"],
                    batch["penalty_output"],
                    batch["presence"],
                    batch["frequency"],
                    batch["repetition"],
                )
            toks = sample_tokens(
                logits,
                batch["temps"],
                batch["top_ps"],
                batch["top_ks"],
                batch["min_ps"],
                batch["seeds"],
            )
            return toks, kv_cache

        # Sampled tokens come back replicated: on a multi-host mesh the
        # primary must be able to device_get them (only addressable shards
        # are fetchable), and an all-gather of [B] int32 is free.
        self._step = jax.jit(
            step,
            donate_argnums=(1,),
            out_shardings=(self._repl, cache_sh),
        )

        bs = cfg.block_size
        drop_slot = self.num_blocks * bs

        def multi_step(params, kv_cache, batch, n_steps: int):
            """Decode ``n_steps`` tokens per sequence in one compiled call.

            The inter-token dependency (sampled token feeds the next forward)
            lives inside a ``lax.scan``: positions, page write slots, and
            per-step PRNG seeds are all derived on-device, so the host pays
            one dispatch per burst instead of per token.
            """
            tables = batch["block_tables"]
            active = batch["kv_lens"] > 0  # padding rows never write

            def body(carry, i):
                kv_cache, tokens, positions = carry
                blk = jnp.take_along_axis(
                    tables, (positions // bs)[:, None], axis=1
                )[:, 0]
                flat = jnp.where(
                    active, blk * bs + positions % bs, drop_slot
                ).astype(jnp.int32)
                logits, kv_cache = model.forward(
                    params,
                    tokens[:, None],
                    positions[:, None],
                    flat[:, None],
                    tables,
                    positions + 1,  # kv valid through the just-written slot
                    jnp.zeros_like(positions),
                    kv_cache,
                    lora_idx=batch.get("lora_idx"),
                    lora_scale=batch.get("lora_scale"),
                    attn_impl=attn_impl,
                    pp_size=pp,
                    mesh=mesh_for_pp,
                )
                nxt = sample_tokens(
                    logits,
                    batch["temps"],
                    batch["top_ps"],
                    batch["top_ks"],
                    batch["min_ps"],
                    batch["seeds"] + i.astype(jnp.uint32),
                )
                return (kv_cache, nxt, positions + 1), nxt

            carry = (kv_cache, batch["tokens"], batch["positions"])
            (kv_cache, _, _), toks = jax.lax.scan(
                body, carry, jnp.arange(n_steps), length=n_steps
            )
            return toks.T, kv_cache  # [B, n_steps]

        self._multi_step = jax.jit(
            multi_step,
            static_argnums=(3,),
            donate_argnums=(1,),
            out_shardings=(self._repl, cache_sh),
        )
        # Multi-host control plane (None on single-host): installed by the
        # server when jax.process_count() > 1; every device dispatch below
        # announces first so followers issue the identical XLA call.
        self.publisher = None
        # Serializes announce+dispatch pairs: the engine step thread and the
        # executor threads serving /v1/embeddings//rerank//score would
        # otherwise interleave broadcasts, diverging the followers' XLA
        # program order from the primary's (collective deadlock).
        self._device_lock = threading.RLock()

    # ------------------------------------------------------------------
    # Page I/O for KV tiering (HBM ↔ host DRAM, the LMCache-offload hook).
    # blk is a traced scalar so each direction compiles exactly once.
    # ------------------------------------------------------------------

    def download_page(self, blk: int):
        """Fetch one page's K/V across all layers → host numpy [L, bs, KH, hd]."""
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("download_page", int(blk))
            return self._dispatch_download_page(blk)

    def _dispatch_download_page(self, blk: int):
        if not hasattr(self, "_page_get"):
            self._page_get = jax.jit(
                lambda c, i: c[:, i], out_shardings=self._repl
            )
        page = np.asarray(jax.device_get(self._page_get(self.kv_cache, blk)))
        L, _, bs, _ = page.shape
        KH, hd = self.model_cfg.num_kv_heads, self.model_cfg.head_dim
        k = page[:, 0].reshape(L, bs, KH, hd)
        v = page[:, 1].reshape(L, bs, KH, hd)
        return k, v

    def upload_page(self, blk: int, k_np, v_np) -> None:
        """Install host page data into HBM page ``blk`` (donated, in-place)."""
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("upload_page", (int(blk), k_np, v_np))
            self._dispatch_upload_page(blk, k_np, v_np)

    def _dispatch_upload_page(self, blk: int, k_np, v_np) -> None:
        if not hasattr(self, "_page_set"):
            self._page_set = jax.jit(
                lambda c, i, x: c.at[:, i].set(x), donate_argnums=(0,)
            )
        k_np, v_np = np.asarray(k_np), np.asarray(v_np)
        L, bs = k_np.shape[0], k_np.shape[1]
        page = np.stack(
            [k_np.reshape(L, bs, -1), v_np.reshape(L, bs, -1)], axis=1
        )  # [L, 2, bs, KH*hd]
        self.kv_cache = self._page_set(
            self.kv_cache, blk, jnp_asarray(page, self.kv_cache.dtype)
        )

    # ------------------------------------------------------------------
    # LoRA bank slots (engine/lora.py owns name->slot; device arrays here)
    # ------------------------------------------------------------------

    def install_adapter(self, slot: int, arrays: Dict[str, Any]) -> None:
        """Write one adapter's A/B matrices into bank slot ``slot``.

        arrays: {target: (A [L, in, r_max], B [L, r_max, out])} host numpy.
        """
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("install_adapter", (int(slot), arrays))
            self._dispatch_install_adapter(slot, arrays)

    def _dispatch_install_adapter(self, slot: int, arrays: Dict[str, Any]) -> None:
        if not hasattr(self, "_slot_set"):
            self._slot_set = jax.jit(
                lambda bank, s, x: bank.at[:, s].set(x), donate_argnums=(0,)
            )
        layers = self.params["layers"]
        for t, (a_np, b_np) in arrays.items():
            for key, host in ((f"lora_a_{t}", a_np), (f"lora_b_{t}", b_np)):
                bank = layers[key]
                layers[key] = self._slot_set(
                    bank, slot, jnp_asarray(host, bank.dtype)
                )

    def uninstall_adapter(self, slot: int) -> None:
        """Zero bank slot ``slot`` (unload: the slot id may be reused)."""
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("uninstall_adapter", int(slot))
            self._dispatch_uninstall_adapter(slot)

    def _dispatch_uninstall_adapter(self, slot: int) -> None:
        if not hasattr(self, "_slot_zero"):
            self._slot_zero = jax.jit(
                lambda bank, s: bank.at[:, s].set(0.0), donate_argnums=(0,)
            )
        layers = self.params["layers"]
        for key in list(layers):
            if key.startswith("lora_"):
                layers[key] = self._slot_zero(layers[key], slot)

    # ------------------------------------------------------------------
    # Sleep / wake (reference tutorial 19: free accelerator memory without
    # restarting the pod; KV contents are discarded, shapes restored on wake)
    # ------------------------------------------------------------------

    def drop_kv_cache(self) -> None:
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("drop_kv", None)
            self._dispatch_drop_kv()

    def _dispatch_drop_kv(self) -> None:
        self.kv_cache.delete()
        self.kv_cache = None

    def restore_kv_cache(self) -> None:
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("restore_kv", None)
            self._dispatch_restore_kv()

    def _dispatch_restore_kv(self) -> None:
        cache_sh = NamedSharding(self.mesh, Llama.cache_pspec(pipeline=self._pp > 1))
        self.kv_cache = jax.device_put(
            self.model.make_kv_cache(
                self.num_blocks, self.cfg.block_size, self.cfg.kv_cache_dtype
            ),
            cache_sh,
        )

    # ------------------------------------------------------------------
    # Embeddings (/v1/embeddings): full-attention encode, mean-pooled
    # ------------------------------------------------------------------

    def encode(self, token_ids: Seq[int]) -> np.ndarray:
        T = _pow2(max(len(token_ids), 1), cap=_pow2(self.cfg.max_model_len))
        toks = np.zeros((1, T), np.int32)
        toks[0, : len(token_ids)] = token_ids
        length = np.array([len(token_ids)], np.int32)
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("encode", (toks, length))
            return self._dispatch_encode(toks, length)

    def _dispatch_encode(self, toks: np.ndarray, length: np.ndarray) -> np.ndarray:
        if not hasattr(self, "_encode_fn"):
            model = self.model
            pp = self._pp
            mesh_for_pp = self.mesh if pp > 1 else None

            def enc(params, toks, length):
                return model.encode(
                    params, toks, length, pp_size=pp, mesh=mesh_for_pp
                )

            self._encode_fn = jax.jit(enc, out_shardings=self._repl)
        out = self._encode_fn(
            self.params,
            jax.device_put(toks, self._repl),
            jax.device_put(length, self._repl),
        )
        return np.asarray(jax.device_get(out))[0]

    # ------------------------------------------------------------------
    # Public entry points
    # ------------------------------------------------------------------

    def execute_decode(self, seqs: List[Sequence]) -> np.ndarray:
        """One decode token for each sequence. Returns [len(seqs)] ids."""
        batch = self._decode_batch(seqs)
        return self._run(batch)[: len(seqs)]

    def execute_decode_multi(self, seqs: List[Sequence], n_steps: int) -> np.ndarray:
        """Decode burst: ``n_steps`` tokens per sequence in one device call.
        Returns [len(seqs), n_steps] token ids (host trims at stops)."""
        if n_steps == 1:
            return self.execute_decode(seqs)[:, None]
        batch = self._decode_batch(seqs, multi=True)
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("multi_step", (batch, n_steps))
            return self._dispatch_multi_step(batch, n_steps)[: len(seqs)]

    def _put_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """ONE device_put for the whole batch tree. Separate puts cost a
        round trip each on remote-attached chips (~1 ms apiece through the
        tunnel — a 12-array batch was paying ~11 ms of pure RPC per step)."""
        B = batch["kv_lens"].shape[0]
        row_shard = self._dp > 1 and B % self._dp == 0
        return jax.device_put(batch, self._row if row_shard else self._repl)

    def _dispatch_multi_step(self, batch: Dict[str, np.ndarray], n_steps: int) -> np.ndarray:
        toks, self.kv_cache = self._multi_step(
            self.params, self.kv_cache, self._put_batch(batch), n_steps
        )
        return np.asarray(jax.device_get(toks))

    def execute_prefill(self, item: PrefillItem) -> int:
        """Process one prefill chunk; returns the sampled token id (only
        meaningful when the chunk completes the prompt)."""
        batch = self._prefill_batch([item])
        return int(self._run(batch)[0])

    def execute_prefill_batch(self, items: List[PrefillItem]) -> np.ndarray:
        """Prefill several chunks in one device call (rows padded to a
        common chunk bucket). Returns [len(items)] sampled token ids."""
        batch = self._prefill_batch(items)
        return self._run(batch)[: len(items)]

    def _run(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("step", batch)
            return self._dispatch_step(batch)

    def _dispatch_step(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        toks, self.kv_cache = self._step(
            self.params, self.kv_cache, self._put_batch(batch)
        )
        return np.asarray(jax.device_get(toks))

    # ------------------------------------------------------------------
    # Batch construction (host side, numpy)
    # ------------------------------------------------------------------

    def _table_row(self, seq: Sequence, width: int) -> np.ndarray:
        row = np.zeros(width, np.int32)
        n = min(len(seq.block_ids), width)
        row[:n] = seq.block_ids[:n]
        return row

    def _decode_batch(
        self, seqs: List[Sequence], multi: bool = False
    ) -> Dict[str, np.ndarray]:
        B = len(seqs)
        Bb = _pow2(B, cap=_pow2(self.cfg.max_num_seqs))
        Bb = max(Bb, B, self._dp, self.cfg.min_decode_bucket)
        W = max(len(s.block_ids) for s in seqs)
        Wb = max(
            _pow2(W, cap=_pow2(self.max_table_width)),
            min(_MIN_TABLE_BUCKET, _pow2(self.max_table_width)),
        )
        bs = self.cfg.block_size

        shape = (Bb,) if multi else (Bb, 1)
        tokens = np.zeros(shape, np.int32)
        positions = np.zeros(shape, np.int32)
        tables = np.zeros((Bb, Wb), np.int32)
        kv_lens = np.zeros(Bb, np.int32)
        if not multi:
            write_idx = np.full((Bb, 1), self._drop_slot, np.int32)
            last_idx = np.zeros(Bb, np.int32)
        for i, s in enumerate(seqs):
            pos = s.num_tokens - 1
            tokens[i, ...] = s.all_token_ids[-1]
            positions[i, ...] = pos
            tables[i] = self._table_row(s, Wb)
            kv_lens[i] = s.num_tokens
            if not multi:
                write_idx[i, 0] = s.block_ids[pos // bs] * bs + pos % bs
        batch = {
            "tokens": tokens,
            "positions": positions,
            "block_tables": tables,
            "kv_lens": kv_lens,
        }
        if not multi:
            batch["write_idx"] = write_idx
            batch["last_idx"] = last_idx
        batch.update(self._sampling_arrays(seqs, Bb))
        return batch

    def _prefill_batch(self, items: List[PrefillItem]) -> Dict[str, np.ndarray]:
        B = len(items)
        Bb = _pow2(B)
        chunk_max = max(it.end - it.start for it in items)
        Tb = _pow2(chunk_max, cap=_pow2(self.cfg.max_prefill_tokens))
        Tb = max(Tb, chunk_max)
        Wb = max(
            _pow2(
                max(max(len(it.seq.block_ids) for it in items), 1),
                cap=_pow2(self.max_table_width),
            ),
            min(_MIN_TABLE_BUCKET, _pow2(self.max_table_width)),
        )
        bs = self.cfg.block_size

        tokens = np.zeros((Bb, Tb), np.int32)
        positions = np.zeros((Bb, Tb), np.int32)
        write_idx = np.full((Bb, Tb), self._drop_slot, np.int32)
        tables = np.zeros((Bb, Wb), np.int32)
        kv_lens = np.zeros(Bb, np.int32)
        last_idx = np.zeros(Bb, np.int32)
        for i, it in enumerate(items):
            s, start, end = it.seq, it.start, it.end
            chunk = end - start
            ids = s.all_token_ids
            for j in range(chunk):
                pos = start + j
                tokens[i, j] = ids[pos]
                positions[i, j] = pos
                write_idx[i, j] = s.block_ids[pos // bs] * bs + pos % bs
            positions[i, chunk:] = max(end - 1, 0)
            tables[i] = self._table_row(s, Wb)
            kv_lens[i] = end
            last_idx[i] = chunk - 1
        batch = {
            "tokens": tokens,
            "positions": positions,
            "write_idx": write_idx,
            "block_tables": tables,
            "kv_lens": kv_lens,
            "last_idx": last_idx,
        }
        batch.update(self._sampling_arrays([it.seq for it in items], Bb))
        return batch

    def _sampling_arrays(
        self, seqs: List[Sequence], B: int
    ) -> Dict[str, np.ndarray]:
        temps = np.zeros(B, np.float32)
        top_ps = np.ones(B, np.float32)
        top_ks = np.zeros(B, np.int32)
        min_ps = np.zeros(B, np.float32)
        seeds = np.zeros(B, np.uint32)
        for i, s in enumerate(seqs):
            sp = s.sampling
            temps[i] = sp.temperature
            top_ps[i] = sp.top_p
            top_ks[i] = sp.top_k
            min_ps[i] = sp.min_p
            seeds[i] = _seed_for(s)
        out = {
            "temps": temps,
            "top_ps": top_ps,
            "top_ks": top_ks,
            "min_ps": min_ps,
            "seeds": seeds,
        }
        if self.cfg.enable_lora:
            lora_idx = np.zeros(B, np.int32)
            lora_scale = np.zeros(B, np.float32)
            for i, s in enumerate(seqs):
                lora_idx[i] = getattr(s, "lora_idx", 0)
                lora_scale[i] = getattr(s, "lora_scale", 0.0)
            out["lora_idx"] = lora_idx
            out["lora_scale"] = lora_scale
        if any(s.sampling.has_penalties for s in seqs):
            out.update(self._penalty_arrays(seqs, B))
        return out

    def _penalty_arrays(
        self, seqs: List[Sequence], B: int
    ) -> Dict[str, np.ndarray]:
        V = self.model_cfg.vocab_size  # pad value: dropped by scatter
        Pp = _pow2(max(max(s.num_prompt_tokens for s in seqs), 1))
        Po = _pow2(max(max(len(s.output_token_ids) for s in seqs), 1))
        prompt = np.full((B, Pp), V, np.int32)
        output = np.full((B, Po), V, np.int32)
        presence = np.zeros(B, np.float32)
        frequency = np.zeros(B, np.float32)
        repetition = np.ones(B, np.float32)
        for i, s in enumerate(seqs):
            sp = s.sampling
            prompt[i, : s.num_prompt_tokens] = s.prompt_token_ids
            output[i, : len(s.output_token_ids)] = s.output_token_ids
            presence[i] = sp.presence_penalty
            frequency[i] = sp.frequency_penalty
            repetition[i] = sp.repetition_penalty
        return {
            "penalty_prompt": prompt,
            "penalty_output": output,
            "presence": presence,
            "frequency": frequency,
            "repetition": repetition,
        }
