"""Model runner: marshals scheduler output into jitted device steps.

Owns the device state (sharded params + KV page arrays) and the compiled
step functions. XLA's static-shape world meets continuous batching here:
every step is padded into power-of-two buckets — decode batch width, prefill
chunk length, block-table width — so the number of distinct compilations is
O(log² shapes), all cached by ``jax.jit``. Padding rows write to a
guaranteed-dropped slot (flat index ``nb*bs``) and are masked in attention by
``kv_len = 0``.

Sampling runs inside the same jit (logits never leave the device); only the
``[B]`` sampled token ids are transferred back.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence as Seq

import jax
import jax.numpy as jnp
import numpy as np
import xxhash
from jax.numpy import asarray as jnp_asarray
from jax.sharding import NamedSharding, PartitionSpec as P

from ..logging_utils import init_logger
from ..obs.engine_telemetry import ENGINE_TELEMETRY, next_runner_scope
from ..models.llama import (
    QUANT4_SUFFIX,
    QUANT_LAYER_KEYS,
    QUANT_SUFFIX,
    QUANT_TOP_KEYS,
    Llama,
    LlamaConfig,
    init_leaf,
    load_hf_params,
    quantize_leaf,
    quantize_leaf_int4,
)
from ..models.registry import get_model_config
from ..ops.sampling import (
    apply_allowed_mask,
    apply_logit_bias,
    apply_penalties,
    apply_penalties_counts,
    sample_tokens_packed,
)
from ..parallel.mesh import MeshConfig, build_mesh
from .config import EngineConfig, resolve_num_kv_blocks
from .scheduler import PrefillItem
from .sequence import Sequence

logger = init_logger(__name__)


def _pow2(n: int, cap: Optional[int] = None) -> int:
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap) if cap else b


# Block tables below this width share one bucket: sequences crossing small
# power-of-two boundaries would otherwise retrace mid-serving, and the pallas
# kernel skips out-of-range pages anyway (only the gather fallback pays for
# the extra width).
_MIN_TABLE_BUCKET = 64


def _fetch(arr) -> np.ndarray:
    """Device→host fetch tuned for remote-attached chips: start the async
    copy, poll readiness, then read through ``jax.device_get``.

    The final read MUST be device_get, not ``np.asarray``: on the tunneled
    backend ``np.asarray`` issues a fresh synchronous transfer RPC every
    call (~45 ms for 128 BYTES) even when the async copy already landed,
    while device_get returns the copied value in ~0.2 ms. Measured
    (scripts/tpu_decode_profile.py methodology, r4): asarray(ready) 46.8 ms
    vs device_get(ready) 0.2 ms — this one line was most of the decode
    step's 80 ms non-compute overhead.

    Poll interval note: isolated probes suggested longer sleeps (5-10 ms)
    can beat tight polling on a single-core host (the loop competes with
    the tunnel client's IO threads), but end-to-end bench runs did not
    reproduce the win against the environment's run-to-run drift — the
    short interval keeps small fetches cheap and measured best overall."""
    try:
        arr.copy_to_host_async()
    except Exception:  # pragma: no cover — backends without async copy
        return np.asarray(jax.device_get(arr))
    while not arr.is_ready():
        # pstlint: disable=async-blocking(0.3 ms device-readiness poll on the engine's dedicated step thread, never on an event loop; see the docstring above for the measured alternatives)
        time.sleep(0.0003)
    return np.asarray(jax.device_get(arr))


def _seed_for(seq: Sequence) -> int:
    base = (
        seq.sampling.seed
        if seq.sampling.seed is not None
        else xxhash.xxh32(seq.request_id.encode()).intdigest()
    )
    return (base + len(seq.output_token_ids)) & 0x7FFF_FFFF


class ModelRunner:
    def __init__(
        self,
        cfg: EngineConfig,
        model_cfg: Optional[LlamaConfig] = None,
        mesh=None,
    ):
        t_init = time.perf_counter()
        # Distinct per-runner telemetry scope: jit caches are per-runner, so
        # a fresh runner's first dispatches are real compiles even when an
        # earlier runner in this process saw identical bucket shapes.
        self._tel_scope = next_runner_scope()
        self.cfg = cfg
        self.model_cfg = model_cfg or get_model_config(cfg.model)
        self.model = Llama(self.model_cfg)
        tp = cfg.tensor_parallel_size
        pp = max(cfg.pipeline_parallel_size, 1)
        self._pp = pp
        if self.model_cfg.num_kv_heads % max(tp, 1):
            raise ValueError(
                f"num_kv_heads={self.model_cfg.num_kv_heads} not divisible by "
                f"tensor_parallel_size={tp}"
            )
        if self.model_cfg.num_layers % pp:
            raise ValueError(
                f"num_layers={self.model_cfg.num_layers} not divisible by "
                f"pipeline_parallel_size={pp}"
            )
        if cfg.sequence_parallel_size > 1 and pp > 1:
            # Fail at startup, not on the first /v1/embeddings request.
            raise ValueError(
                "sequence_parallel_size > 1 (ring encode) does not compose "
                "with pipeline_parallel_size > 1 yet"
            )
        ep = max(cfg.expert_parallel_size, 1)
        if ep > 1 and (
            not self.model_cfg.num_experts
            or self.model_cfg.num_experts % ep
        ):
            raise ValueError(
                f"expert_parallel_size={ep} needs a MoE model with "
                f"num_experts divisible by it "
                f"(model has {self.model_cfg.num_experts})"
            )
        self.mesh = mesh or build_mesh(
            MeshConfig(
                tensor_parallel_size=tp,
                data_parallel_size=cfg.data_parallel_size,
                pipeline_parallel_size=pp,
                sequence_parallel_size=max(cfg.sequence_parallel_size, 1),
                expert_parallel_size=ep,
            )
        )

        t0 = time.time()
        quant = cfg.quantization or None
        if quant not in (None, "int8", "int4"):
            raise ValueError(
                f"unsupported quantization {quant!r} (int8 or int4)"
            )
        self._quant = quant
        pspecs = self.model.param_pspecs(pipeline=pp > 1, quantize=quant or False)
        if cfg.enable_lora:
            pspecs["layers"].update(self.model.lora_pspecs(pipeline=pp > 1))
        if os.path.isdir(cfg.model):
            # quantize=True stages + quantizes in numpy on the host: the
            # bf16 tree of an 8B model never exists in HBM next to the int8
            # one (and no CPU JAX backend is needed under a pinned
            # JAX_PLATFORMS).
            params = load_hf_params(
                self.model_cfg, cfg.model, quantize=quant or False
            )
        elif quant:
            # Preset (random-init) + quantized: materialize leaf-by-leaf
            # straight into device shardings — peak HBM is the int8 tree
            # plus one transient bf16 leaf. (Includes the LoRA bank; no
            # host-side tree to device_put below.)
            params = None
            self.params = self._init_params_streamed(pspecs)
        else:
            params = self.model.init_params(jax.random.PRNGKey(cfg.seed))
        if params is not None:
            if cfg.enable_lora:
                params["layers"].update(
                    self.model.init_lora_bank(cfg.max_loras, cfg.max_lora_rank)
                )
            self.params = jax.tree.map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(self.mesh, self._fit_spec(s, x.shape, x.dtype))
                ),
                params,
                pspecs,
            )
        leaves = jax.tree.leaves(self.params)
        self.param_count = sum(x.size for x in leaves)
        param_bytes = sum(x.size * x.dtype.itemsize for x in leaves)
        # Total weight bytes as resident (post-quantization): the decode
        # roofline's per-step weight-read term (benchmarks/bench_engine.py).
        self.param_bytes = param_bytes
        logger.info(
            "params ready: %.2f GiB total, %.1fs", param_bytes / 2**30, time.time() - t0
        )
        # Startup decomposition, phase 1: parameter materialization
        # (pst_engine_startup_seconds{phase="load"}).
        t_load_end = time.perf_counter()
        ENGINE_TELEMETRY.record_startup_phase("load", t_load_end - t_init)
        ENGINE_TELEMETRY.set_model_info(
            self.param_count,
            device_kind=getattr(jax.local_devices()[0], "device_kind", None),
        )

        self.num_blocks = resolve_num_kv_blocks(
            cfg, self.model_cfg, param_bytes // (max(tp, 1) * pp)
        )
        self.max_table_width = -(-cfg.max_model_len // cfg.block_size)
        cache_sh = NamedSharding(self.mesh, Llama.cache_pspec(pipeline=pp > 1))
        self._dispatch_restore_kv()  # single source of truth for allocation
        self._repl = NamedSharding(self.mesh, P())
        # Decode batches shard rows over dp (independent sequences — the
        # in-engine data-parallel axis); prefill chunks stay replicated.
        self._dp = cfg.data_parallel_size
        self._row = NamedSharding(self.mesh, P("dp"))
        self._drop_slot = self.num_blocks * cfg.block_size

        model = self.model
        attn_impl = cfg.attn_impl
        mesh_for_pp = self.mesh if pp > 1 else None
        # MoE strategy: ragged_dot is the FLOP-proportional single-shard
        # path; whenever the expert bank is mesh-sharded (ep/tp/pp) use the
        # dense einsum formulation, whose contractions GSPMD partitions
        # cleanly (ragged_dot has no partitioning rule — XLA would gather
        # the full bank to every device).
        moe_impl = cfg.moe_impl
        if moe_impl == "auto":
            mesh_shape = dict(self.mesh.shape)
            sharded = (
                mesh_shape.get("ep", 1) > 1 or tp > 1 or pp > 1
            )
            moe_impl = "dense" if sharded else "ragged"
        self._moe_impl = moe_impl

        def step(
            params, kv_cache, batch: Dict[str, Any], want_lp: bool,
            greedy: bool,
        ):
            logits, kv_cache = model.forward(
                params,
                batch["tokens"],
                batch["positions"],
                batch["write_idx"],
                batch["block_tables"],
                batch["kv_lens"],
                batch["last_idx"],
                kv_cache,
                lora_idx=batch.get("lora_idx"),
                lora_scale=batch.get("lora_scale"),
                attn_impl=attn_impl,
                moe_impl=moe_impl,
                pp_size=pp,
                mesh=mesh_for_pp,
            )
            if "penalty_prompt" in batch:
                logits = apply_penalties(
                    logits,
                    batch["penalty_prompt"],
                    batch["penalty_output"],
                    batch["presence"],
                    batch["frequency"],
                    batch["repetition"],
                )
            if "bias_ids" in batch:
                logits = apply_logit_bias(
                    logits, batch["bias_ids"], batch["bias_vals"]
                )
            if "allowed_ids" in batch:
                logits = apply_allowed_mask(
                    logits, batch["allowed_ids"], batch["allow_free"]
                )
            # Packed rows: [token] or [token, chosen_lp, top_lps,
            # top_ids] — one fetch serves both sampling and logprobs, and
            # the logprobs math compiles in only when requested.
            packed = sample_tokens_packed(
                logits,
                batch["temps"],
                batch["top_ps"],
                batch["top_ks"],
                batch["min_ps"],
                batch["seeds"],
                with_logprobs=want_lp,
                greedy_only=greedy,
            )
            return packed, kv_cache

        # Sampled tokens come back replicated: on a multi-host mesh the
        # primary must be able to device_get them (only addressable shards
        # are fetchable), and an all-gather of [B] int32 is free.
        # pstlint: jit-family=decode,prefill
        self._step = jax.jit(
            step,
            static_argnums=(3, 4),
            donate_argnums=(1,),
            out_shardings=(self._repl, cache_sh),
        )

        bs = cfg.block_size
        drop_slot = self.num_blocks * bs

        def multi_step(params, kv_cache, batch, tokens, positions, seed_off,
                       pen_counts, n_steps: int, want_lp: bool, greedy: bool,
                       with_pen: bool):
            """Decode ``n_steps`` tokens per sequence in one compiled call.

            The inter-token dependency (sampled token feeds the next forward)
            lives inside a ``lax.scan``: positions, page write slots, and
            per-step PRNG seeds are all derived on-device. ``tokens`` /
            ``positions`` / ``seed_off`` are explicit [B]/[B]/scalar inputs
            and are returned advanced, so a FOLLOW-UP burst can chain from
            the previous burst's device outputs with zero host round trips —
            the basis of pipelined decode (one burst always in flight, its
            fetch overlapped with the next burst's execution).

            ``pen_counts`` ([B, V] output-token occurrence counts, or a
            [1, 1] placeholder when ``with_pen`` is False) rides the scan
            carry and is returned advanced: each sampled token increments
            its own count ON DEVICE, so penalty/repetition rows decode at
            full burst depth — and a pipelined continuation chains the
            counts without ever rebuilding them host-side."""
            tables = batch["block_tables"]
            active = batch["kv_lens"] > 0  # padding rows never write

            def body(carry, i):
                kv_cache, tokens, positions, so, counts = carry
                blk = jnp.take_along_axis(
                    tables, (positions // bs)[:, None], axis=1
                )[:, 0]
                flat = jnp.where(
                    active, blk * bs + positions % bs, drop_slot
                ).astype(jnp.int32)
                logits, kv_cache = model.forward(
                    params,
                    tokens[:, None],
                    positions[:, None],
                    flat[:, None],
                    tables,
                    positions + 1,  # kv valid through the just-written slot
                    jnp.zeros_like(positions),
                    kv_cache,
                    lora_idx=batch.get("lora_idx"),
                    lora_scale=batch.get("lora_scale"),
                    attn_impl=attn_impl,
                    moe_impl=moe_impl,
                    pp_size=pp,
                    mesh=mesh_for_pp,
                )
                if with_pen:
                    logits = apply_penalties_counts(
                        logits,
                        batch["penalty_seen"],
                        counts,
                        batch["presence"],
                        batch["frequency"],
                        batch["repetition"],
                    )
                if "bias_ids" in batch:
                    logits = apply_logit_bias(
                        logits, batch["bias_ids"], batch["bias_vals"]
                    )
                packed = sample_tokens_packed(
                    logits,
                    batch["temps"],
                    batch["top_ps"],
                    batch["top_ks"],
                    batch["min_ps"],
                    batch["seeds"] + so,
                    with_logprobs=want_lp,
                    greedy_only=greedy,
                )
                nxt = packed[:, 0].astype(jnp.int32)
                if with_pen:
                    counts = counts.at[
                        jnp.arange(counts.shape[0], dtype=jnp.int32), nxt
                    ].add(active.astype(jnp.float32))
                return (kv_cache, nxt, positions + 1, so + 1, counts), packed

            carry = (kv_cache, tokens, positions, seed_off, pen_counts)
            (kv_cache, tokens, positions, seed_off, pen_counts), packed = (
                jax.lax.scan(body, carry, jnp.arange(n_steps), length=n_steps)
            )
            # [n, B, W] -> [B, n, W]
            return (
                packed.transpose(1, 0, 2), tokens, positions, seed_off,
                pen_counts, kv_cache,
            )

        # pstlint: jit-family=decode_burst
        self._multi_step = jax.jit(
            multi_step,
            static_argnums=(7, 8, 9, 10),
            donate_argnums=(1,),
            out_shardings=(
                self._repl, self._repl, self._repl, self._repl, self._repl,
                cache_sh,
            ),
        )
        # Pipelined-burst state: device handles of the burst in flight.
        self._burst = None
        # Per-request cost attribution (docs/observability.md "Cost
        # attribution"): when on, every dispatch's measured wall is split
        # across the sequences it served (token-weighted for prefill,
        # active-row share for decode/verify) so request costs sum to the
        # device-busy wall.
        self._cost_enabled = bool(cfg.cost_attribution)
        # Host-gap accounting: perf_counter stamp of the moment the last
        # decode step's tokens became host-visible with the device idle
        # (pst_engine_host_gap_seconds measures from here to the next
        # decode dispatch — the serial host bookkeeping on the critical
        # path that the overlapped pipeline exists to hide).
        self._host_gap_t0: Optional[float] = None
        # Multi-host control plane (None on single-host): installed by the
        # server when jax.process_count() > 1; every device dispatch below
        # announces first so followers issue the identical XLA call.
        self.publisher = None
        # Serializes announce+dispatch pairs: the engine step thread and the
        # executor threads serving /v1/embeddings//rerank//score would
        # otherwise interleave broadcasts, diverging the followers' XLA
        # program order from the primary's (collective deadlock).
        self._device_lock = threading.RLock()
        # Startup decomposition, phase 2: device placement + KV-cache
        # allocation + jit wiring (pst_engine_startup_seconds{phase="shard"}).
        ENGINE_TELEMETRY.record_startup_phase(
            "shard", time.perf_counter() - t_load_end
        )

    # ------------------------------------------------------------------
    # Streamed param materialization (quantized presets)
    # ------------------------------------------------------------------

    # Leaves above this replicate-instead-of-shard threshold still raise on
    # non-divisible dims: silently replicating a multi-GB weight across tp
    # would turn a clear startup misconfiguration into a distant OOM.
    _FIT_SPEC_MAX_BYTES = 4 << 20

    def _fit_spec(self, spec: P, shape, dtype=None) -> P:
        """Drop sharding on SMALL axes the array's dims don't divide
        (replicate instead). Real serving shapes always divide; tiny debug
        models can end up with e.g. 2 int4 scale groups under tp=4 —
        replicating a few-KB scale there beats failing the mesh placement.
        Big leaves keep the loud divisibility error."""
        ent = list(spec) + [None] * (len(shape) - len(spec))
        for i, ax in enumerate(ent):
            if ax is None:
                continue
            axes = (ax,) if isinstance(ax, str) else tuple(ax)
            size = 1
            for a in axes:
                size *= self.mesh.shape[a]
            if shape[i] % size:
                nbytes = int(np.prod(shape)) * (
                    np.dtype(dtype).itemsize if dtype is not None else 4
                )
                if nbytes > self._FIT_SPEC_MAX_BYTES:
                    raise ValueError(
                        f"param leaf of shape {tuple(shape)} ({nbytes>>20} MiB)"
                        f" is not divisible by mesh axis {ax!r}"
                        f" (size {size}) on dim {i}; refusing to replicate a"
                        " large leaf — fix the parallelism config"
                    )
                logger.debug(
                    "replicating small leaf %s on mesh axis %r "
                    "(dim %d=%d not divisible by %d)",
                    tuple(shape), ax, i, shape[i], size,
                )
                ent[i] = None
        return P(*ent)

    def _init_params_streamed(self, pspecs: Dict[str, Any]) -> Dict[str, Any]:
        """Random-init params leaf-by-leaf, each jitted directly into its
        device sharding and (for matmul weights) quantized to int8 on
        device before the next leaf materializes. Peak HBM = final int8
        tree + ONE transient bf16 leaf — how an 8B preset initializes on a
        16 GiB chip where the bf16 tree alone would OOM."""
        cfg = self.cfg
        rng = jax.random.PRNGKey(cfg.seed)
        shapes = jax.eval_shape(self.model.init_params, rng)
        if cfg.enable_lora:
            shapes["layers"].update(
                jax.eval_shape(
                    functools.partial(
                        self.model.init_lora_bank,
                        cfg.max_loras,
                        cfg.max_lora_rank,
                    )
                )
            )

        def build(name, sds, specs_at, into):
            key = jax.random.fold_in(
                rng, xxhash.xxh32(name.encode()).intdigest() & 0x7FFF_FFFF
            )
            # Per-layer matmuls follow the configured mode (int8 or group-
            # wise int4); embed/lm_head stay per-channel int8 in both modes.
            int4 = self._quant == "int4" and name in QUANT_LAYER_KEYS
            qaxis = (
                -2 if name in QUANT_LAYER_KEYS
                else -1 if name in QUANT_TOP_KEYS
                else None
            )
            if qaxis is None:
                # pstlint: disable=recompile-risk(parameter materialization runs once at startup inside the load phase, before /ready — it can never be a live-traffic compile)
                into[name] = jax.jit(
                    functools.partial(init_leaf, name, sds.shape, sds.dtype),
                    out_shardings=NamedSharding(
                        self.mesh, self._fit_spec(specs_at[name], sds.shape, sds.dtype)
                    ),
                )(key)
                return

            def init_q(k):  # one jit per leaf: init + quantize fused
                w = init_leaf(name, sds.shape, sds.dtype, k)
                return (
                    quantize_leaf_int4(w) if int4
                    else quantize_leaf(w, axis=qaxis)
                )

            qname = name + (QUANT4_SUFFIX if int4 else QUANT_SUFFIX)
            q_sds, s_sds = jax.eval_shape(init_q, key)
            # pstlint: disable=recompile-risk(weight quantization runs once at startup inside the load phase, before /ready — it can never be a live-traffic compile)
            q, s = jax.jit(
                init_q,
                out_shardings=(
                    NamedSharding(
                        self.mesh, self._fit_spec(specs_at[name], q_sds.shape, q_sds.dtype)
                    ),
                    NamedSharding(
                        self.mesh, self._fit_spec(specs_at[qname], s_sds.shape, s_sds.dtype)
                    ),
                ),
            )(key)
            into[name], into[qname] = q, s

        out: Dict[str, Any] = {"layers": {}}
        for name, sds in shapes.items():
            if name == "layers":
                continue
            build(name, sds, pspecs, out)
        for name, sds in shapes["layers"].items():
            build(name, sds, pspecs["layers"], out["layers"])
        return out

    # ------------------------------------------------------------------
    # Page I/O for KV tiering (HBM ↔ host DRAM, the LMCache-offload hook).
    # blk is a traced scalar so each direction compiles exactly once.
    # ------------------------------------------------------------------

    def download_page(self, blk: int):
        """Fetch one page's K/V across all layers → host numpy [L, bs, KH, hd]."""
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("download_page", int(blk))
            return self._dispatch_download_page(blk)

    def _dispatch_download_page(self, blk: int):
        if not hasattr(self, "_page_get"):
            # pstlint: disable=recompile-risk(KV page download is a fixed-shape maintenance op — one compile per engine lifetime at first swap-out, off the TTFT path)
            self._page_get = jax.jit(
                lambda c, i: c[:, i], out_shardings=self._repl
            )
        page = _fetch(self._page_get(self.kv_cache, blk))
        L, _, bs, _ = page.shape
        KH, hd = self.model_cfg.num_kv_heads, self.model_cfg.head_dim
        k = page[:, 0].reshape(L, bs, KH, hd)
        v = page[:, 1].reshape(L, bs, KH, hd)
        return k, v

    def upload_page(self, blk: int, k_np, v_np) -> None:
        """Install host page data into HBM page ``blk`` (donated, in-place)."""
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("upload_page", (int(blk), k_np, v_np))
            self._dispatch_upload_page(blk, k_np, v_np)

    def _dispatch_upload_page(self, blk: int, k_np, v_np) -> None:
        if not hasattr(self, "_page_set"):
            # pstlint: disable=recompile-risk(KV page upload is a fixed-shape maintenance op — one compile per engine lifetime at first swap-in, off the TTFT path)
            self._page_set = jax.jit(
                lambda c, i, x: c.at[:, i].set(x), donate_argnums=(0,)
            )
        k_np, v_np = np.asarray(k_np), np.asarray(v_np)
        L, bs = k_np.shape[0], k_np.shape[1]
        page = np.stack(
            [k_np.reshape(L, bs, -1), v_np.reshape(L, bs, -1)], axis=1
        )  # [L, 2, bs, KH*hd]
        self.kv_cache = self._page_set(
            self.kv_cache, blk, jnp_asarray(page, self.kv_cache.dtype)
        )

    # ------------------------------------------------------------------
    # LoRA bank slots (engine/lora.py owns name->slot; device arrays here)
    # ------------------------------------------------------------------

    def install_adapter(self, slot: int, arrays: Dict[str, Any]) -> None:
        """Write one adapter's A/B matrices into bank slot ``slot``.

        arrays: {target: (A [L, in, r_max], B [L, r_max, out])} host numpy.
        """
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("install_adapter", (int(slot), arrays))
            self._dispatch_install_adapter(slot, arrays)

    def _dispatch_install_adapter(self, slot: int, arrays: Dict[str, Any]) -> None:
        if not hasattr(self, "_slot_set"):
            # pstlint: disable=recompile-risk(LoRA bank install is a fixed-shape admin op paid on adapter load, not on live decode)
            self._slot_set = jax.jit(
                lambda bank, s, x: bank.at[:, s].set(x), donate_argnums=(0,)
            )
        layers = self.params["layers"]
        for t, (a_np, b_np) in arrays.items():
            for key, host in ((f"lora_a_{t}", a_np), (f"lora_b_{t}", b_np)):
                bank = layers[key]
                layers[key] = self._slot_set(
                    bank, slot, jnp_asarray(host, bank.dtype)
                )

    def uninstall_adapter(self, slot: int) -> None:
        """Zero bank slot ``slot`` (unload: the slot id may be reused)."""
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("uninstall_adapter", int(slot))
            self._dispatch_uninstall_adapter(slot)

    def _dispatch_uninstall_adapter(self, slot: int) -> None:
        if not hasattr(self, "_slot_zero"):
            # pstlint: disable=recompile-risk(LoRA bank zeroing is a fixed-shape admin op paid on adapter unload, not on live decode)
            self._slot_zero = jax.jit(
                lambda bank, s: bank.at[:, s].set(0.0), donate_argnums=(0,)
            )
        layers = self.params["layers"]
        for key in list(layers):
            if key.startswith("lora_"):
                layers[key] = self._slot_zero(layers[key], slot)

    # ------------------------------------------------------------------
    # Sleep / wake (reference tutorial 19: free accelerator memory without
    # restarting the pod; KV contents are discarded, shapes restored on wake)
    # ------------------------------------------------------------------

    def drop_kv_cache(self) -> None:
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("drop_kv", None)
            self._dispatch_drop_kv()

    def _dispatch_drop_kv(self) -> None:
        self.kv_cache.delete()
        self.kv_cache = None

    def restore_kv_cache(self) -> None:
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("restore_kv", None)
            self._dispatch_restore_kv()

    def _dispatch_restore_kv(self) -> None:
        cache_sh = NamedSharding(self.mesh, Llama.cache_pspec(pipeline=self._pp > 1))
        self.kv_cache = jax.device_put(
            self.model.make_kv_cache(
                self.num_blocks, self.cfg.block_size, self.cfg.kv_cache_dtype
            ),
            cache_sh,
        )

    # ------------------------------------------------------------------
    # Embeddings (/v1/embeddings): full-attention encode, mean-pooled
    # ------------------------------------------------------------------

    def encode(self, token_ids: Seq[int]) -> np.ndarray:
        T = _pow2(max(len(token_ids), 1), cap=_pow2(self.cfg.max_model_len))
        # Ring encode shards T over sp: round the bucket UP to a multiple
        # (a power of two is never divisible by e.g. sp=3).
        sp = max(self.cfg.sequence_parallel_size, 1)
        T = -(-T // sp) * sp
        toks = np.zeros((1, T), np.int32)
        toks[0, : len(token_ids)] = token_ids
        length = np.array([len(token_ids)], np.int32)
        key = (self._tel_scope, "encode", T)
        t0 = time.perf_counter()
        self._host_gap_cancel()
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("encode", (toks, length))
            out = self._dispatch_encode(toks, length)
        ENGINE_TELEMETRY.record_dispatch(
            "encode", key, time.perf_counter() - t0,
            batch_bucket=f"t{T}", tokens=len(token_ids),
            fill_ratio=len(token_ids) / max(T, 1),
        )
        return out

    def _dispatch_encode(self, toks: np.ndarray, length: np.ndarray) -> np.ndarray:
        if not hasattr(self, "_encode_fn"):
            model = self.model
            pp = self._pp
            sp = max(self.cfg.sequence_parallel_size, 1)
            mesh = self.mesh if (pp > 1 or sp > 1) else None

            moe_impl = self._moe_impl

            def enc(params, toks, length):
                return model.encode(
                    params, toks, length, pp_size=pp, sp_size=sp,
                    moe_impl=moe_impl, mesh=mesh,
                )

            # pstlint: jit-family=encode
            self._encode_fn = jax.jit(enc, out_shardings=self._repl)
        out = self._encode_fn(
            self.params,
            jax.device_put(toks, self._repl),
            jax.device_put(length, self._repl),
        )
        return _fetch(out)[0]

    # ------------------------------------------------------------------
    # Public entry points
    # ------------------------------------------------------------------

    @staticmethod
    def _want_lp(seqs: List[Sequence]) -> bool:
        return any(s.sampling.logprobs is not None for s in seqs)

    @staticmethod
    def _all_greedy(seqs: List[Sequence]) -> bool:
        """True when every row is greedy: the compiled step then skips the
        full sampling machinery (static fast path in ops/sampling.py)."""
        return all(s.sampling.greedy for s in seqs)

    def _tel_key(
        self, kind: str, batch: Dict[str, np.ndarray], extras: tuple = ()
    ) -> tuple:
        """Shape-bucket signature for compile detection: the padded array
        shapes plus the static jit flags are exactly what keys the XLA
        executable cache, so a fresh signature means a fresh compile."""
        shapes = tuple(sorted((k, np.shape(v)) for k, v in batch.items()))
        return (self._tel_scope, kind, shapes, extras)

    # -- per-request cost attribution ------------------------------------

    def _charge_decode(self, seqs: List[Sequence], seconds: float) -> None:
        """Split one decode/verify dispatch's wall equally across its
        ACTIVE rows (padding rows and already-finished pipeline members
        cost nothing; shares sum to the step wall, so pipelined
        continuations never double-count — each wall segment is charged
        exactly once)."""
        if not self._cost_enabled or seconds <= 0:
            return
        alive = [s for s in seqs if not s.is_finished]
        if not alive:
            return
        share = seconds / len(alive)
        now = time.monotonic()
        for s in alive:
            s.cost_decode_s += share
            s.charge_kv_pages(now)

    def _charge_prefill(self, items: List[PrefillItem], seconds: float) -> None:
        """Split one prefill step's wall across its chunks by real-token
        weight (a 2k-token chunk sharing a step with a 64-token one pays
        accordingly)."""
        if not self._cost_enabled or seconds <= 0 or not items:
            return
        total = sum(it.end - it.start for it in items)
        if total <= 0:
            return
        now = time.monotonic()
        for it in items:
            it.seq.cost_prefill_s += seconds * (it.end - it.start) / total
            it.seq.charge_kv_pages(now)

    # -- host-gap accounting (pst_engine_host_gap_seconds) ---------------

    def _host_gap_mark(
        self, bucket: str, t_dispatch: float, seqs=None
    ) -> None:
        """Close the open host gap at a decode dispatch: the wall between
        the previous decode step's completion and this dispatch is pure
        serial host bookkeeping (batch build, detok, stop scans, scheduler
        accounting) that idled the device. One sequence of the dispatching
        burst rides along as the histogram exemplar (a slow gap bucket
        links to the request timeline that absorbed it)."""
        t0, self._host_gap_t0 = self._host_gap_t0, None
        if t0 is not None:
            ENGINE_TELEMETRY.record_host_gap(
                bucket, t_dispatch - t0,
                request_id=seqs[0].request_id if seqs else None,
            )

    def _host_gap_arm(self) -> None:
        """A decode step's tokens just became host-visible with no further
        device work queued: the host gap starts now."""
        self._host_gap_t0 = time.perf_counter()

    def _host_gap_cancel(self) -> None:
        """A non-decode dispatch (prefill/spec/encode) intervened: the
        decode→decode gap is no longer host bookkeeping — drop it."""
        self._host_gap_t0 = None

    def execute_decode(self, seqs: List[Sequence]) -> np.ndarray:
        """One decode step per sequence. Returns packed sample rows
        [len(seqs), 1 or PACKED_WIDTH] (token [+ logprobs]; ops/sampling.py)."""
        batch = self._decode_batch(seqs)
        want_lp, greedy = self._want_lp(seqs), self._all_greedy(seqs)
        key = self._tel_key("decode", batch, (want_lp, greedy))
        Bb = batch["kv_lens"].shape[0]
        t0 = time.perf_counter()
        self._host_gap_mark(f"b{Bb}", t0, seqs)
        rows = self._run(batch, want_lp, greedy)
        self._host_gap_arm()
        dt = time.perf_counter() - t0
        self._charge_decode(seqs, dt)
        ENGINE_TELEMETRY.record_dispatch(
            "decode", key, dt,
            batch_bucket=f"b{Bb}", tokens=len(seqs),
            fill_ratio=len(seqs) / Bb,
        )
        return rows[: len(seqs)]

    def execute_decode_multi(self, seqs: List[Sequence], n_steps: int) -> np.ndarray:
        """Decode burst: ``n_steps`` tokens per sequence in one device call.
        Returns packed rows [len(seqs), n_steps, PACKED_WIDTH] (host trims
        at stops)."""
        if n_steps == 1:
            return self.execute_decode(seqs)[:, None]
        batch = self._decode_batch(seqs, multi=True)
        # Guided-choice masks are rebuilt per token host-side; the scan body
        # cannot apply them. The scheduler forces n=1 for guided rows — fail
        # loudly if that invariant ever breaks instead of dropping the mask
        # (RuntimeError, not assert: must survive `python -O`).
        if "allowed_ids" in batch:
            raise RuntimeError(
                "guided-choice rows reached a multi-step decode burst"
            )
        counts = self._penalty_counts_for(seqs, batch)
        want_lp = self._want_lp(seqs)
        greedy = self._all_greedy(seqs)
        key = self._tel_key("decode", batch, (n_steps, want_lp, greedy))
        Bb = batch["kv_lens"].shape[0]
        t0 = time.perf_counter()
        self._host_gap_mark(f"b{Bb}xn{n_steps}", t0, seqs)
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce(
                    "multi_step", (batch, counts, n_steps, want_lp, greedy)
                )
            rows = self._dispatch_multi_step(
                batch, counts, n_steps, want_lp, greedy
            )
        self._host_gap_arm()
        dt = time.perf_counter() - t0
        self._charge_decode(seqs, dt)
        ENGINE_TELEMETRY.record_dispatch(
            "decode", key, dt,
            batch_bucket=f"b{Bb}xn{n_steps}", tokens=len(seqs) * n_steps,
            fill_ratio=len(seqs) / Bb,
        )
        return rows[: len(seqs)]

    def _penalty_counts_for(
        self, seqs: List[Sequence], batch: Dict[str, np.ndarray]
    ) -> np.ndarray:
        """Dense penalty state for a multi-step batch, replacing the
        token-id arrays ``_sampling_arrays`` builds for the single-step
        path: ``penalty_seen`` [Bb, V] bool (prompt occurrence — constant
        over the whole burst/pipeline) goes INTO the batch, and the
        returned [Bb, V] float32 output-token counts ride ``multi_step``'s
        scan carry. Dense state keeps the executable's trace signature
        independent of prompt/output lengths (one penalized variant per
        bucket, not one per pow2 length). Returns the [1, 1] placeholder
        when no row is penalized."""
        if not any(s.sampling.has_penalties for s in seqs):
            # The id-array penalty fields are only built when a row is
            # penalized; nothing to strip.
            return np.zeros((1, 1), np.float32)
        Bb = batch["kv_lens"].shape[0]
        V = self.model_cfg.vocab_size
        seen = np.zeros((Bb, V), bool)
        counts = np.zeros((Bb, V), np.float32)
        for i, s in enumerate(seqs):
            ids = np.asarray(s.prompt_token_ids, np.int64)
            seen[i, ids[(ids >= 0) & (ids < V)]] = True
            if s.output_token_ids:
                out = np.asarray(s.output_token_ids, np.int64)
                uniq, cnt = np.unique(
                    out[(out >= 0) & (out < V)], return_counts=True
                )
                counts[i, uniq] = cnt
        # Replace the pow2-length id arrays with the dense form.
        batch.pop("penalty_prompt", None)
        batch.pop("penalty_output", None)
        batch["penalty_seen"] = seen
        return counts

    def _put_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """ONE device_put for the whole batch tree. Separate puts cost a
        round trip each on remote-attached chips (~1 ms apiece through the
        tunnel — a 12-array batch was paying ~11 ms of pure RPC per step)."""
        B = batch["kv_lens"].shape[0]
        row_shard = self._dp > 1 and B % self._dp == 0
        return jax.device_put(batch, self._row if row_shard else self._repl)

    def _dispatch_multi_step(
        self,
        batch: Dict[str, np.ndarray],
        counts: np.ndarray,
        n_steps: int,
        want_lp: bool = False,
        greedy: bool = False,
    ) -> np.ndarray:
        dev = self._put_batch(batch)
        seed0 = jax.device_put(np.zeros((), np.uint32), self._repl)
        cdev = jax.device_put(counts, self._repl)
        tokens = dev.pop("tokens")
        positions = dev.pop("positions")
        with_pen = "penalty_seen" in batch
        toks, _, _, _, _, self.kv_cache = self._multi_step(
            self.params, self.kv_cache, dev, tokens, positions, seed0,
            cdev, n_steps, want_lp, greedy, with_pen,
        )
        return _fetch(toks)

    # ------------------------------------------------------------------
    # Pipelined decode bursts: one burst always in flight; its token fetch
    # overlaps the next burst's execution, hiding the host<->device round
    # trip (~70 ms on tunnel-attached chips, the decode-latency floor of a
    # synchronous loop).
    # ------------------------------------------------------------------

    @property
    def burst_in_flight(self) -> bool:
        return self._burst is not None

    def burst_start(self, seqs: List[Sequence], n_steps: int) -> None:
        """Dispatch the first burst of a pipeline (async; nothing fetched)."""
        if self._burst is not None:
            raise RuntimeError("burst already in flight (drain first)")
        batch = self._decode_batch(seqs, multi=True)
        if "allowed_ids" in batch:
            raise RuntimeError(
                "guided-choice rows reached a pipelined decode burst"
            )
        counts = self._penalty_counts_for(seqs, batch)
        want_lp = self._want_lp(seqs)
        greedy = self._all_greedy(seqs)
        key = self._tel_key("decode", batch, (n_steps, want_lp, greedy))
        Bb = batch["kv_lens"].shape[0]
        bucket = f"b{Bb}xn{n_steps}"
        t0 = time.perf_counter()
        self._host_gap_mark(bucket, t0, seqs)
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce(
                    "burst_start", (batch, counts, n_steps, want_lp, greedy)
                )
            self._dispatch_burst_start(batch, counts, n_steps, want_lp, greedy)
        dt = time.perf_counter() - t0
        self._charge_decode(seqs, dt)
        ENGINE_TELEMETRY.record_dispatch(
            "decode", key, dt,
            batch_bucket=bucket, tokens=len(seqs) * n_steps,
            fill_ratio=len(seqs) / Bb,
        )
        # Continuations re-dispatch the same executable: keep the signature
        # so their step timings land in the same bucket without re-counting
        # a compile.
        self._burst_tel = (key, bucket, Bb, n_steps)

    def _dispatch_burst_start(
        self,
        batch: Dict[str, np.ndarray],
        counts: np.ndarray,
        n_steps: int,
        want_lp: bool = False,
        greedy: bool = False,
    ) -> None:
        dev = self._put_batch(batch)
        seed = jax.device_put(np.zeros((), np.uint32), self._repl)
        cdev = jax.device_put(counts, self._repl)
        tokens = dev.pop("tokens")
        positions = dev.pop("positions")
        with_pen = "penalty_seen" in batch
        toks, tokens, positions, seed, cdev, self.kv_cache = self._multi_step(
            self.params, self.kv_cache, dev, tokens, positions, seed,
            cdev, n_steps, want_lp, greedy, with_pen,
        )
        try:  # start the host copy NOW; the eventual fetch finds it resident
            toks.copy_to_host_async()
        except Exception:  # pragma: no cover
            pass
        self._burst = {
            "batch": dev, "tokens": tokens, "positions": positions,
            "seed": seed, "counts": cdev, "with_pen": with_pen,
            "toks": toks, "n": n_steps, "want_lp": want_lp,
            "greedy": greedy,
        }

    def burst_width_stable(self, members: List[Sequence]) -> bool:
        """True while the members' block tables still fit the width bucket
        the in-flight burst compiled with (growth past it needs a drain)."""
        if self._burst is None:
            return False
        Wb = self._burst["batch"]["block_tables"].shape[1]
        return max(len(s.block_ids) for s in members) <= Wb

    def burst_continue(self, members: List[Sequence]) -> np.ndarray:
        """Dispatch the NEXT burst, then fetch and return the PREVIOUS
        burst's tokens [Bb, n] (the fetch overlaps the new burst's
        execution). ``members`` is the pipeline's original membership, in
        order: their block tables are refreshed (the scheduler reserves
        lookahead pages host-side; the device table must see them) and
        members that finished host-side get kv_len 0 so their speculative
        rows stop writing KV."""
        assert self._burst is not None
        Wb = self._burst["batch"]["block_tables"].shape[1]
        Bb = self._burst["batch"]["kv_lens"].shape[0]
        tables = np.zeros((Bb, Wb), np.int32)
        kv_lens = np.zeros(Bb, np.int32)
        for i, s in enumerate(members):
            tables[i] = self._table_row(s, Wb)
            kv_lens[i] = 0 if s.is_finished else max(s.num_tokens, 1)
        alive = sum(1 for s in members if not s.is_finished)
        t0 = time.perf_counter()
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("burst_cont", (tables, kv_lens))
            rows = self._dispatch_burst_continue(tables, kv_lens)
        tel = getattr(self, "_burst_tel", None)
        if tel is not None:
            # The continuation was dispatched BEFORE the previous burst's
            # tokens were even read: the device runs the two back-to-back,
            # so the host gap on this step is — by construction — zero.
            # Recording it keeps the histogram's percentiles honest about
            # what the pipeline removed (not silently absent at steady
            # state).
            ENGINE_TELEMETRY.record_host_gap(tel[1], 0.0)
            key, bucket, rows_b, n = tel
            dt = time.perf_counter() - t0
            # The continuation wall (dispatch next + overlapped fetch of
            # the previous burst) is charged ONCE across the members still
            # alive — the share of the just-fetched burst's device time.
            self._charge_decode(members, dt)
            # pstlint: disable=recompile-risk(key and bucket are carried verbatim from burst_start's registered _tel_key via _burst_tel — a continuation re-dispatches the same executable, so the shape identity cannot drift)
            ENGINE_TELEMETRY.record_dispatch(
                "decode", key, dt,
                batch_bucket=bucket, tokens=alive * n,
                fill_ratio=alive / max(rows_b, 1),
            )
        return rows

    def _dispatch_burst_continue(
        self, tables: np.ndarray, kv_lens: np.ndarray
    ) -> np.ndarray:
        st = self._burst
        prev = st["toks"]
        st["batch"].update(
            self._put_batch({"block_tables": tables, "kv_lens": kv_lens})
        )
        toks, tokens, positions, seed, counts, self.kv_cache = self._multi_step(
            self.params, self.kv_cache, st["batch"], st["tokens"],
            st["positions"], st["seed"], st["counts"], st["n"],
            st["want_lp"], st.get("greedy", False), st.get("with_pen", False),
        )
        try:  # start the host copy NOW; the eventual fetch finds it resident
            toks.copy_to_host_async()
        except Exception:  # pragma: no cover
            pass
        st.update(
            tokens=tokens, positions=positions, seed=seed, counts=counts,
            toks=toks,
        )
        return _fetch(prev)

    def burst_drain(self) -> np.ndarray:
        """Fetch the in-flight burst's tokens and end the pipeline."""
        assert self._burst is not None
        st, self._burst = self._burst, None
        # No device op, so no multihost announce: followers hold no pending
        # fetch (they never read tokens) and their next announced dispatch
        # keeps program order identical.
        rows = _fetch(st["toks"])
        # Drains are transitions (an arrival or shape change broke the
        # pipeline) and a prefill may already be queued behind this fetch —
        # the wall from here to the next decode dispatch is not steady-state
        # host bookkeeping, so the gap clock does not run across it.
        self._host_gap_cancel()
        return rows

    def execute_spec_verify(
        self, seqs: List[Sequence], drafts: np.ndarray
    ) -> "tuple[np.ndarray, np.ndarray]":
        """Speculative-decoding verify step: score each sequence's last
        committed token plus its K draft tokens in ONE forward pass.

        ``drafts`` is [B, K] int32. Returns ``(argmax_ids [B, K+1],
        sampled0 [B])`` — row j's argmax is the token the model itself would
        emit after consuming positions ≤ p0+j (the engine compares it
        against the drafts to count acceptances), and ``sampled0`` is
        position 0 put through the full sampling pipeline (temperature /
        top-p / seeds / logit_bias), so draftless rows in a mixed batch get
        exactly the token a plain decode step would have produced. KV for
        all K+1 positions is written during the pass; rejected positions
        sit past the committed kv_len and are overwritten on real decode.
        """
        B, K = drafts.shape
        batch = self._spec_batch(seqs, drafts)
        key = self._tel_key("spec_verify", batch, (K,))
        Bb = batch["kv_lens"].shape[0]
        t0 = time.perf_counter()
        self._host_gap_cancel()
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("spec_verify", batch)
            ids, sampled0 = self._dispatch_spec_verify(batch)
        dt = time.perf_counter() - t0
        self._charge_decode(seqs, dt)
        ENGINE_TELEMETRY.record_dispatch(
            "spec_verify", key, dt,
            batch_bucket=f"b{Bb}xk{K}", tokens=len(seqs) * (K + 1),
            fill_ratio=len(seqs) / Bb,
        )
        return ids[: len(seqs)], sampled0[: len(seqs)]

    def _spec_batch(
        self, seqs: List[Sequence], drafts: np.ndarray
    ) -> Dict[str, np.ndarray]:
        B, K = drafts.shape
        T = K + 1
        Bb = self._row_bucket(B)
        Wb = self._table_bucket(seqs)
        bs = self.cfg.block_size
        tokens = np.zeros((Bb, T), np.int32)
        positions = np.zeros((Bb, T), np.int32)
        write_idx = np.full((Bb, T), self._drop_slot, np.int32)
        tables = np.zeros((Bb, Wb), np.int32)
        kv_lens = np.zeros(Bb, np.int32)
        last_idx = np.zeros(Bb, np.int32)
        for i, s in enumerate(seqs):
            p0 = s.num_tokens - 1  # the not-yet-computed last token
            # Direct last-token read: all_token_ids would rebuild the full
            # prompt+output list per row per step (O(context) host work).
            tokens[i, 0] = (
                s.output_token_ids[-1]
                if s.output_token_ids
                else s.prompt_token_ids[-1]
            )
            tokens[i, 1:] = drafts[i]
            positions[i] = p0 + np.arange(T, dtype=np.int32)
            covered = len(s.block_ids) * bs  # draftless near-limit rows may
            for j in range(T):  # not have pages for all K+1 positions
                pos = p0 + j
                if pos < covered:
                    write_idx[i, j] = s.block_ids[pos // bs] * bs + pos % bs
            tables[i] = self._table_row(s, Wb)
            kv_lens[i] = min(s.num_tokens + K, covered)
        batch = {
            "tokens": tokens,
            "positions": positions,
            "write_idx": write_idx,
            "block_tables": tables,
            "kv_lens": kv_lens,
            "last_idx": last_idx,
        }
        # Full sampling arrays: position 0 is sampled exactly like a plain
        # decode step (draftless rows in a mixed batch rely on this), and
        # LoRA rows verify WITH their adapter.
        batch.update(self._sampling_arrays(seqs, Bb))
        batch.pop("penalty_prompt", None)  # penalized rows never reach spec
        batch.pop("penalty_output", None)
        batch.pop("presence", None)
        batch.pop("frequency", None)
        batch.pop("repetition", None)
        return batch

    def _dispatch_spec_verify(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        if not hasattr(self, "_spec_step"):
            model = self.model
            attn_impl = self.cfg.attn_impl
            pp = self._pp
            mesh_for_pp = self.mesh if pp > 1 else None
            moe_impl = self._moe_impl

            def spec_step(params, kv_cache, batch):
                logits, kv_cache = model.forward(
                    params,
                    batch["tokens"],
                    batch["positions"],
                    batch["write_idx"],
                    batch["block_tables"],
                    batch["kv_lens"],
                    batch["last_idx"],
                    kv_cache,
                    lora_idx=batch.get("lora_idx"),
                    lora_scale=batch.get("lora_scale"),
                    attn_impl=attn_impl,
                    moe_impl=moe_impl,
                    pp_size=pp,
                    mesh=mesh_for_pp,
                    all_logits=True,
                )  # [B, T, V] fp32
                if "bias_ids" in batch:
                    # logit_bias applies at EVERY verified position (a
                    # biased greedy row's accept chain must follow the
                    # biased argmax).
                    logits = jax.vmap(
                        apply_logit_bias, in_axes=(1, None, None), out_axes=1
                    )(logits, batch["bias_ids"], batch["bias_vals"])
                ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, T]
                logits0 = logits[:, 0]
                if "allowed_ids" in batch:  # guided rows ride draftless
                    logits0 = apply_allowed_mask(
                        logits0, batch["allowed_ids"], batch["allow_free"]
                    )
                packed0 = sample_tokens_packed(
                    logits0,
                    batch["temps"],
                    batch["top_ps"],
                    batch["top_ks"],
                    batch["min_ps"],
                    batch["seeds"],
                    with_logprobs=False,
                )
                sampled0 = packed0[:, 0].astype(jnp.int32)  # [B]
                # ONE output array = ONE host fetch (a second fetch costs a
                # full round trip on tunnel-attached chips): column K+1
                # carries the sampled position-0 token.
                return jnp.concatenate([ids, sampled0[:, None]], axis=1), kv_cache

            cache_sh = NamedSharding(
                self.mesh, Llama.cache_pspec(pipeline=pp > 1)
            )
            # pstlint: jit-family=spec_verify
            self._spec_step = jax.jit(
                spec_step,
                donate_argnums=(1,),
                out_shardings=(self._repl, cache_sh),
            )
        packed, self.kv_cache = self._spec_step(
            self.params, self.kv_cache, self._put_batch(batch)
        )
        packed = _fetch(packed)
        return packed[:, :-1], packed[:, -1]

    def _prefill_tel(
        self, items: List[PrefillItem], batch: Dict[str, np.ndarray],
        extras: tuple,
    ) -> tuple:
        """(shape key, bucket label, real tokens, fill ratio) for one
        prefill step's telemetry."""
        Bb, Tb = batch["tokens"].shape
        real = sum(it.end - it.start for it in items)
        return (
            self._tel_key("prefill", batch, extras),
            f"b{Bb}xt{Tb}",
            real,
            real / max(Bb * Tb, 1),
        )

    def execute_prefill(self, item: PrefillItem) -> int:
        """Process one prefill chunk; returns the sampled token id (only
        meaningful when the chunk completes the prompt)."""
        return int(self.execute_prefill_batch([item])[0, 0])

    def execute_prefill_batch(self, items: List[PrefillItem]) -> np.ndarray:
        """Prefill several chunks in one device call (rows padded to a
        common chunk bucket). Returns packed sample rows
        [len(items), 1 or PACKED_WIDTH] (token [+ logprobs])."""
        seqs = [i.seq for i in items]
        batch = self._prefill_batch(items)
        want_lp, greedy = self._want_lp(seqs), self._all_greedy(seqs)
        key, bucket, real, fill = self._prefill_tel(
            items, batch, (want_lp, greedy)
        )
        t0 = time.perf_counter()
        self._host_gap_cancel()
        rows = self._run(batch, want_lp, greedy)
        dt = time.perf_counter() - t0
        self._charge_prefill(items, dt)
        ENGINE_TELEMETRY.record_dispatch(
            "prefill", key, dt,
            batch_bucket=bucket, tokens=real, fill_ratio=fill,
        )
        return rows[: len(items)]

    def execute_prefill_batch_nofetch(self, items: List[PrefillItem]) -> None:
        """Dispatch a prefill step WITHOUT fetching its sampled tokens.

        Intermediate chunks of a long prompt sample nothing anyone reads
        (only the prompt-completing chunk's token matters), yet a fetch
        costs a full host<->device round trip — on tunnel-attached chips
        that synchronization dominated cold prefill (~70 ms x ~20 chunks
        per 20k-token prompt). The KV writes chain on-device through the
        donated cache, so correctness is unaffected; the next fetching step
        transitively waits for all queued work."""
        batch = self._prefill_batch(items)
        # nofetch steps compile as (want_lp=False, greedy=True) — the same
        # executable a fetching greedy step uses.
        key, bucket, real, fill = self._prefill_tel(items, batch, (False, True))
        t0 = time.perf_counter()
        self._host_gap_cancel()
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("step_nofetch", batch)
            self._dispatch_step_nofetch(batch)
        dt = time.perf_counter() - t0
        self._charge_prefill(items, dt)
        ENGINE_TELEMETRY.record_dispatch(
            "prefill", key, dt,
            batch_bucket=bucket, tokens=real, fill_ratio=fill,
        )

    def _dispatch_step_nofetch(self, batch: Dict[str, np.ndarray]) -> None:
        # greedy=True: nobody reads an intermediate chunk's sample, so the
        # cheapest sampling variant (plain argmax) is always correct here.
        _, self.kv_cache = self._step(
            self.params, self.kv_cache, self._put_batch(batch), False, True
        )

    def prefill_dispatch(self, items: List[PrefillItem]):  # noqa: D401
        """Async half of a prefill step: dispatch and return the device
        handle without fetching. Used to slip a new arrival's prefill in
        BEHIND an in-flight decode burst (the device serializes them; the
        burst drain then overlaps the prefill's execution), cutting one full
        host<->device round trip out of TTFT."""
        batch = self._prefill_batch(items)
        want_lp = self._want_lp([i.seq for i in items])
        greedy = self._all_greedy([i.seq for i in items])
        key, bucket, real, fill = self._prefill_tel(
            items, batch, (want_lp, greedy)
        )
        t0 = time.perf_counter()
        self._host_gap_cancel()
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("step", (batch, want_lp, greedy))
            dev = self._put_batch(batch)
            toks, self.kv_cache = self._step(
                self.params, self.kv_cache, dev, want_lp, greedy
            )
        dt = time.perf_counter() - t0
        self._charge_prefill(items, dt)
        ENGINE_TELEMETRY.record_dispatch(
            "prefill", key, dt,
            batch_bucket=bucket, tokens=real, fill_ratio=fill,
        )
        try:
            toks.copy_to_host_async()
        except Exception:  # pragma: no cover
            pass
        return toks

    def prefill_fetch(self, handle, n_items: int) -> np.ndarray:
        return _fetch(handle)[:n_items]

    def _run(
        self,
        batch: Dict[str, np.ndarray],
        want_lp: bool = False,
        greedy: bool = False,
    ) -> np.ndarray:
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("step", (batch, want_lp, greedy))
            return self._dispatch_step(batch, want_lp, greedy)

    def _dispatch_step(
        self,
        batch: Dict[str, np.ndarray],
        want_lp: bool = False,
        greedy: bool = False,
    ) -> np.ndarray:
        toks, self.kv_cache = self._step(
            self.params, self.kv_cache, self._put_batch(batch), want_lp, greedy
        )
        return _fetch(toks)

    # ------------------------------------------------------------------
    # Warmup precompilation (engine/precompile.py drives this)
    # ------------------------------------------------------------------

    def _warmup_sampling_arrays(self, B: int) -> Dict[str, np.ndarray]:
        """The sampling-array tree every live batch carries, all-neutral.
        Shapes and dtypes must match ``_sampling_arrays`` exactly — they
        are part of both the jit trace and the telemetry shape key."""
        out: Dict[str, np.ndarray] = {
            "temps": np.zeros(B, np.float32),
            "top_ps": np.ones(B, np.float32),
            "top_ks": np.zeros(B, np.int32),
            "min_ps": np.zeros(B, np.float32),
            "seeds": np.zeros(B, np.uint32),
        }
        if self.cfg.enable_lora:
            out["lora_idx"] = np.zeros(B, np.int32)
            out["lora_scale"] = np.zeros(B, np.float32)
        return out

    def warmup_bucket(self, bucket) -> None:
        """Compile one lattice bucket with an all-padding dummy batch.

        Every row carries ``kv_len = 0`` and writes to the drop slot, so
        the dispatch touches no real KV state; the shapes and static jit
        flags are exactly what live traffic produces, so both jax.jit's
        executable cache AND the telemetry shape registry treat the
        bucket as already-seen when a real batch arrives — a warmed shape
        can never count as a live-traffic compile again."""
        kind = bucket.kind
        if kind == "decode":
            self._warmup_decode(bucket)
        elif kind == "decode_burst":
            self._warmup_decode_burst(bucket)
        elif kind == "prefill":
            self._warmup_prefill(bucket)
        elif kind == "spec_verify":
            self._warmup_spec_verify(bucket)
        elif kind == "encode":
            self._warmup_encode(bucket)
        else:
            raise ValueError(f"unknown warmup bucket kind {kind!r}")

    def _record_warmup(self, kind: str, key: tuple, seconds: float,
                       label: str) -> None:
        # tokens=0: warmup moves no real tokens, so the throughput window
        # and MFU stay honest; the compile itself is counted (it is one).
        # count_busy=False: warmup serves no request, so it stays out of
        # the device-busy denominator and the flight ring (a warmup pass
        # would otherwise flood the ring with compile snapshots).
        ENGINE_TELEMETRY.record_dispatch(
            kind, key, seconds, batch_bucket=label, tokens=0,
            count_busy=False,
        )

    def _warmup_decode(self, bucket) -> None:
        Bb, Wb = bucket.rows, bucket.width
        batch = {
            "tokens": np.zeros((Bb, 1), np.int32),
            "positions": np.zeros((Bb, 1), np.int32),
            "block_tables": np.zeros((Bb, Wb), np.int32),
            "kv_lens": np.zeros(Bb, np.int32),
            "write_idx": np.full((Bb, 1), self._drop_slot, np.int32),
            "last_idx": np.zeros(Bb, np.int32),
        }
        batch.update(self._warmup_sampling_arrays(Bb))
        key = self._tel_key("decode", batch, (bucket.want_lp, bucket.greedy))
        t0 = time.perf_counter()
        self._run(batch, bucket.want_lp, bucket.greedy)
        self._record_warmup(
            "decode", key, time.perf_counter() - t0, bucket.label
        )

    def _warmup_decode_burst(self, bucket) -> None:
        Bb, Wb, n = bucket.rows, bucket.width, bucket.n_steps
        batch = {
            "tokens": np.zeros(Bb, np.int32),
            "positions": np.zeros(Bb, np.int32),
            "block_tables": np.zeros((Bb, Wb), np.int32),
            "kv_lens": np.zeros(Bb, np.int32),
        }
        batch.update(self._warmup_sampling_arrays(Bb))
        if getattr(bucket, "penalized", False):
            # The dense penalty form _penalty_counts_for builds for live
            # penalized bursts: all-neutral state, exact same shapes.
            V = self.model_cfg.vocab_size
            batch["penalty_seen"] = np.zeros((Bb, V), bool)
            batch["presence"] = np.zeros(Bb, np.float32)
            batch["frequency"] = np.zeros(Bb, np.float32)
            batch["repetition"] = np.ones(Bb, np.float32)
            counts = np.zeros((Bb, V), np.float32)
        else:
            counts = np.zeros((1, 1), np.float32)
        key = self._tel_key(
            "decode", batch, (n, bucket.want_lp, bucket.greedy)
        )
        t0 = time.perf_counter()
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce(
                    "multi_step",
                    (batch, counts, n, bucket.want_lp, bucket.greedy),
                )
            self._dispatch_multi_step(
                batch, counts, n, bucket.want_lp, bucket.greedy
            )
        self._record_warmup(
            "decode", key, time.perf_counter() - t0, bucket.label
        )

    def _warmup_prefill(self, bucket) -> None:
        Bb, Tb, Wb = bucket.rows, bucket.tokens, bucket.width
        batch = {
            "tokens": np.zeros((Bb, Tb), np.int32),
            "positions": np.zeros((Bb, Tb), np.int32),
            "write_idx": np.full((Bb, Tb), self._drop_slot, np.int32),
            "block_tables": np.zeros((Bb, Wb), np.int32),
            "kv_lens": np.zeros(Bb, np.int32),
            "last_idx": np.zeros(Bb, np.int32),
        }
        batch.update(self._warmup_sampling_arrays(Bb))
        key = self._tel_key("prefill", batch, (bucket.want_lp, bucket.greedy))
        t0 = time.perf_counter()
        self._run(batch, bucket.want_lp, bucket.greedy)
        self._record_warmup(
            "prefill", key, time.perf_counter() - t0, bucket.label
        )

    def _warmup_spec_verify(self, bucket) -> None:
        Bb, K, Wb = bucket.rows, bucket.tokens, bucket.width
        T = K + 1
        batch = {
            "tokens": np.zeros((Bb, T), np.int32),
            "positions": np.zeros((Bb, T), np.int32),
            "write_idx": np.full((Bb, T), self._drop_slot, np.int32),
            "block_tables": np.zeros((Bb, Wb), np.int32),
            "kv_lens": np.zeros(Bb, np.int32),
            "last_idx": np.zeros(Bb, np.int32),
        }
        batch.update(self._warmup_sampling_arrays(Bb))
        key = self._tel_key("spec_verify", batch, (K,))
        t0 = time.perf_counter()
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("spec_verify", batch)
            self._dispatch_spec_verify(batch)
        self._record_warmup(
            "spec_verify", key, time.perf_counter() - t0, bucket.label
        )

    def _warmup_encode(self, bucket) -> None:
        T = bucket.tokens
        toks = np.zeros((1, T), np.int32)
        length = np.array([1], np.int32)  # 1, not 0: mean-pool divides by it
        key = (self._tel_scope, "encode", T)
        t0 = time.perf_counter()
        with self._device_lock:
            if self.publisher is not None:
                self.publisher.announce("encode", (toks, length))
            self._dispatch_encode(toks, length)
        self._record_warmup(
            "encode", key, time.perf_counter() - t0, bucket.label
        )

    # ------------------------------------------------------------------
    # Batch construction (host side, numpy)
    # ------------------------------------------------------------------

    def _table_row(self, seq: Sequence, width: int) -> np.ndarray:
        row = np.zeros(width, np.int32)
        n = min(len(seq.block_ids), width)
        row[:n] = seq.block_ids[:n]
        return row

    def _row_bucket(self, B: int) -> int:
        """Decode/verify batch-row bucket: pow2, floored by dp divisibility
        and the compile-stability floor."""
        Bb = _pow2(B, cap=_pow2(self.cfg.max_num_seqs))
        return max(Bb, B, self._dp, self.cfg.min_decode_bucket)

    def _table_bucket(self, seqs: List[Sequence]) -> int:
        W = max(max(len(s.block_ids) for s in seqs), 1)
        return max(
            _pow2(W, cap=_pow2(self.max_table_width)),
            min(_MIN_TABLE_BUCKET, _pow2(self.max_table_width)),
        )

    def _lora_arrays(self, seqs: List[Sequence], B: int) -> Dict[str, np.ndarray]:
        lora_idx = np.zeros(B, np.int32)
        lora_scale = np.zeros(B, np.float32)
        for i, s in enumerate(seqs):
            lora_idx[i] = getattr(s, "lora_idx", 0)
            lora_scale[i] = getattr(s, "lora_scale", 0.0)
        return {"lora_idx": lora_idx, "lora_scale": lora_scale}

    def _decode_batch(
        self, seqs: List[Sequence], multi: bool = False
    ) -> Dict[str, np.ndarray]:
        B = len(seqs)
        Bb = self._row_bucket(B)
        Wb = self._table_bucket(seqs)
        bs = self.cfg.block_size

        shape = (Bb,) if multi else (Bb, 1)
        tokens = np.zeros(shape, np.int32)
        positions = np.zeros(shape, np.int32)
        tables = np.zeros((Bb, Wb), np.int32)
        kv_lens = np.zeros(Bb, np.int32)
        if not multi:
            write_idx = np.full((Bb, 1), self._drop_slot, np.int32)
            last_idx = np.zeros(Bb, np.int32)
        for i, s in enumerate(seqs):
            pos = s.num_tokens - 1
            tokens[i, ...] = s.all_token_ids[-1]
            positions[i, ...] = pos
            tables[i] = self._table_row(s, Wb)
            kv_lens[i] = s.num_tokens
            if not multi:
                write_idx[i, 0] = s.block_ids[pos // bs] * bs + pos % bs
        batch = {
            "tokens": tokens,
            "positions": positions,
            "block_tables": tables,
            "kv_lens": kv_lens,
        }
        if not multi:
            batch["write_idx"] = write_idx
            batch["last_idx"] = last_idx
        batch.update(self._sampling_arrays(seqs, Bb))
        return batch

    def _prefill_batch(self, items: List[PrefillItem]) -> Dict[str, np.ndarray]:
        B = len(items)
        Bb = _pow2(B)
        chunk_max = max(it.end - it.start for it in items)
        Tb = _pow2(chunk_max, cap=_pow2(self.cfg.max_prefill_tokens))
        Tb = max(Tb, chunk_max)
        Wb = self._table_bucket([it.seq for it in items])
        bs = self.cfg.block_size

        tokens = np.zeros((Bb, Tb), np.int32)
        positions = np.zeros((Bb, Tb), np.int32)
        write_idx = np.full((Bb, Tb), self._drop_slot, np.int32)
        tables = np.zeros((Bb, Wb), np.int32)
        kv_lens = np.zeros(Bb, np.int32)
        last_idx = np.zeros(Bb, np.int32)
        for i, it in enumerate(items):
            s, start, end = it.seq, it.start, it.end
            chunk = end - start
            ids = s.all_token_ids
            for j in range(chunk):
                pos = start + j
                tokens[i, j] = ids[pos]
                positions[i, j] = pos
                write_idx[i, j] = s.block_ids[pos // bs] * bs + pos % bs
            positions[i, chunk:] = max(end - 1, 0)
            tables[i] = self._table_row(s, Wb)
            kv_lens[i] = end
            last_idx[i] = chunk - 1
        batch = {
            "tokens": tokens,
            "positions": positions,
            "write_idx": write_idx,
            "block_tables": tables,
            "kv_lens": kv_lens,
            "last_idx": last_idx,
        }
        batch.update(self._sampling_arrays([it.seq for it in items], Bb))
        return batch

    def _sampling_arrays(
        self, seqs: List[Sequence], B: int
    ) -> Dict[str, np.ndarray]:
        temps = np.zeros(B, np.float32)
        top_ps = np.ones(B, np.float32)
        top_ks = np.zeros(B, np.int32)
        min_ps = np.zeros(B, np.float32)
        seeds = np.zeros(B, np.uint32)
        for i, s in enumerate(seqs):
            sp = s.sampling
            temps[i] = sp.temperature
            top_ps[i] = sp.top_p
            top_ks[i] = sp.top_k
            min_ps[i] = sp.min_p
            seeds[i] = _seed_for(s)
        out = {
            "temps": temps,
            "top_ps": top_ps,
            "top_ks": top_ks,
            "min_ps": min_ps,
            "seeds": seeds,
        }
        if self.cfg.enable_lora:
            out.update(self._lora_arrays(seqs, B))
        if any(s.sampling.has_penalties for s in seqs):
            out.update(self._penalty_arrays(seqs, B))
        if any(s.sampling.guided_choice for s in seqs):
            V = self.model_cfg.vocab_size  # pad id: dropped by the scatter
            per_row = [
                s.sampling.guided_allowed(
                    s.output_token_ids, self.model_cfg.eos_token_ids
                )
                for s in seqs
            ]
            Na = _pow2(max(max((len(a) for a in per_row if a), default=1), 1))
            allowed_ids = np.full((B, Na), V, np.int32)
            allow_free = np.ones(B, bool)
            for i, allowed in enumerate(per_row):
                if allowed is None:
                    continue
                allow_free[i] = False
                for j, tid in enumerate(allowed[:Na]):
                    allowed_ids[i, j] = tid
            out["allowed_ids"] = allowed_ids
            out["allow_free"] = allow_free
        if any(s.sampling.logit_bias for s in seqs):
            V = self.model_cfg.vocab_size  # pad id: dropped by the scatter
            Nb = _pow2(max(max(len(s.sampling.logit_bias) for s in seqs), 1))
            bias_ids = np.full((B, Nb), V, np.int32)
            bias_vals = np.zeros((B, Nb), np.float32)
            for i, s in enumerate(seqs):
                for j, (tid, bv) in enumerate(s.sampling.logit_bias[:Nb]):
                    if 0 <= tid < V:
                        bias_ids[i, j] = tid
                        bias_vals[i, j] = bv
            out["bias_ids"] = bias_ids
            out["bias_vals"] = bias_vals
        return out

    def _penalty_arrays(
        self, seqs: List[Sequence], B: int
    ) -> Dict[str, np.ndarray]:
        V = self.model_cfg.vocab_size  # pad value: dropped by scatter
        Pp = _pow2(max(max(s.num_prompt_tokens for s in seqs), 1))
        Po = _pow2(max(max(len(s.output_token_ids) for s in seqs), 1))
        prompt = np.full((B, Pp), V, np.int32)
        output = np.full((B, Po), V, np.int32)
        presence = np.zeros(B, np.float32)
        frequency = np.zeros(B, np.float32)
        repetition = np.ones(B, np.float32)
        for i, s in enumerate(seqs):
            sp = s.sampling
            prompt[i, : s.num_prompt_tokens] = s.prompt_token_ids
            output[i, : len(s.output_token_ids)] = s.output_token_ids
            presence[i] = sp.presence_penalty
            frequency[i] = sp.frequency_penalty
            repetition[i] = sp.repetition_penalty
        return {
            "penalty_prompt": prompt,
            "penalty_output": output,
            "presence": presence,
            "frequency": frequency,
            "repetition": repetition,
        }
