"""Streamed disagg-prefill KV handoff (docs/disagg.md).

The serial pre-handoff flow was: prefill finishes → every committed page is
pushed one sync HTTP PUT at a time → the decode engine re-fetches each page
with its own sync GET at admission. This module makes the transfer a
*streamed, overlapped pipeline* keyed by the router's request id:

- :class:`KVHandoffPublisher` (producer engine): as each prefill chunk's
  pages commit, the step thread downloads them (device→host DMA, same as
  the spill path) and enqueues them; a worker thread ships them in batched
  ``POST /blocks`` round trips and appends their hashes to the request's
  manifest. When the prefill pass completes, a completion marker with the
  total block count lands on the manifest — the decode side's "last block"
  signal. The step thread never blocks on DCN.

- :class:`KVHandoffPrefetcher` (decode engine): long-polls the manifest
  *while the prefill is still running*, batch-fetches each newly published
  block into the tiered allocator's host pool, and returns as soon as the
  completion marker is seen and every block landed — at which point the
  sequence admits with its whole prompt a host-tier prefix hit and the
  first decode step dispatches immediately. A manifest timeout or a dead
  kvserver degrades to plain admission (the engine recomputes the prefill
  — the fused path), never an error.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from ..logging_utils import init_logger

logger = init_logger(__name__)

# One publish batch per manifest append: bounds worker-loop latency so the
# decode side sees progress at chunk granularity, not at prefill granularity.
PUBLISH_BATCH_BLOCKS = 32
# Bound on queued publish entries (chunk batches + completion markers): a
# slow-but-healthy kvserver must never let device-downloaded pages pile up
# in host RAM — same rationale as the spill path's bounded push queue. An
# overflowing transfer is marked failed (the decode side falls back to its
# local recompute) instead of growing without bound.
PUBLISH_QUEUE_CAP = 1024


class KVHandoffPublisher:
    """Streams a disagg prefill's KV pages to the remote block store.

    Thread contract: ``publish``/``complete`` are called on the engine step
    thread (cheap: device→host download + deque append); all HTTP runs on
    the worker thread. Failure flips the per-request ``failed`` flag — the
    manifest then never completes and the decode side times out into its
    fused fallback; nothing here can stall a prefill.
    """

    def __init__(self, remote) -> None:
        self.remote = remote
        self._queue: "collections.deque[tuple]" = collections.deque()
        self._event = threading.Event()
        self._stop = threading.Event()
        # pstlint: owned-by=lock:_lock
        self._failed: set = set()
        self._lock = threading.Lock()
        self.published_blocks = 0
        self.publish_failures = 0
        self.transfer_seconds = 0.0
        self._thread = threading.Thread(
            target=self._worker, name="kv-handoff-publish", daemon=True
        )
        self._thread.start()

    def _overloaded(self, request_id: str) -> bool:
        if len(self._queue) < PUBLISH_QUEUE_CAP:
            return False
        # The worker cannot keep up (slow DCN, not failing HTTP): shed
        # THIS transfer rather than buffering unbounded host copies of
        # device pages — its manifest never completes and the decode side
        # recomputes (the fused fallback).
        self._mark_failed(request_id)
        return True

    def publish(
        self,
        request_id: str,
        pages: List[Tuple[int, np.ndarray, np.ndarray]],
    ) -> None:
        """Enqueue one prefill chunk's freshly committed pages."""
        if not pages or self._overloaded(request_id):
            return
        self._queue.append(("pages", request_id, pages))
        self._event.set()

    def complete(self, request_id: str, total_blocks: int) -> None:
        """The prefill pass finished: append the completion marker after
        every already-enqueued page batch."""
        if self._overloaded(request_id):
            return
        self._queue.append(("complete", request_id, total_blocks))
        self._event.set()

    def shutdown(self) -> None:
        self._stop.set()
        self._event.set()
        self._thread.join(timeout=2.0)

    def _mark_failed(self, request_id: str) -> None:
        with self._lock:
            self._failed.add(request_id)
            if len(self._failed) > 4096:  # bounded: old ids age out
                self._failed = set(list(self._failed)[-2048:])
        self.publish_failures += 1

    def _is_failed(self, request_id: str) -> bool:
        with self._lock:
            return request_id in self._failed

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                kind, rid, payload = self._queue.popleft()
            except IndexError:
                self._event.wait(timeout=0.5)
                self._event.clear()
                continue
            if self._is_failed(rid):
                continue  # transfer already broken: drop the rest
            t0 = time.monotonic()
            if kind == "pages":
                pages = payload
                # Batch within a chunk; a chunk larger than the batch cap
                # still ships in a handful of round trips, not per-page.
                ok = True
                for i in range(0, len(pages), PUBLISH_BATCH_BLOCKS):
                    batch = pages[i : i + PUBLISH_BATCH_BLOCKS]
                    if not self.remote.put_blocks(batch):
                        ok = False
                        break
                if ok:
                    ok = self.remote.post_manifest(
                        rid, [h for h, _, _ in pages]
                    )
                if ok:
                    self.published_blocks += len(pages)
                else:
                    self._mark_failed(rid)
            else:  # complete
                if not self.remote.post_manifest(
                    rid, [], complete=True, total_blocks=payload
                ):
                    self._mark_failed(rid)
            self.transfer_seconds += time.monotonic() - t0


class KVHandoffPrefetcher:
    """Pulls a disagg prefill's published KV while the prefill still runs.

    Blocking (requests-based) by design — the engine HTTP layer runs it in
    an executor thread; everything here is bounded by ``timeout_s``.
    """

    def __init__(self, remote, host_pool, timeout_s: float = 10.0,
                 depth: int = 64) -> None:
        self.remote = remote
        self.host_pool = host_pool
        self.timeout_s = timeout_s
        # Max blocks fetched per batched GET: bounds one response's memory.
        self.depth = max(int(depth), 1)
        self.prefetched_blocks = 0
        self.fallbacks = 0

    def prefetch(
        self, request_id: str, deadline: Optional[float] = None
    ) -> dict:
        """Follow ``request_id``'s manifest to completion, batch-fetching
        published blocks into the host pool as they appear.

        Returns ``{"complete": bool, "blocks": n, "wall_s": s}`` —
        ``complete=False`` means the caller should admit anyway (fused
        fallback: the prefill recomputes locally)."""
        t0 = time.monotonic()
        expire = t0 + self.timeout_s
        if deadline is not None:
            expire = min(expire, deadline)
        have = 0
        fetched = 0
        complete = False
        total: Optional[int] = None
        while True:
            remaining = expire - time.monotonic()
            if remaining <= 0:
                break
            view = self.remote.get_manifest(
                request_id,
                wait_s=min(remaining, 1.0),
                have=have,
                timeout=min(remaining + 2.0, self.timeout_s),
            )
            if view is None:
                # Unknown id (prefill not started publishing yet) or the
                # kvserver died: brief pause, retry until the window ends.
                # pstlint: disable=async-blocking(20 ms manifest re-poll on the consumer prefetch path, which the HTTP layer always runs in an executor thread — never on the event loop; the whole loop is bounded by timeout_s)
                time.sleep(min(0.02, max(remaining, 0.0)))
                continue
            hashes = view.get("hashes") or []
            new = hashes[have:]
            for i in range(0, len(new), self.depth):
                batch = new[i : i + self.depth]
                pages = self.remote.get_blocks(
                    batch, timeout=max(expire - time.monotonic(), 0.001),
                    source="prefetch",
                )
                for h, (k, v) in pages.items():
                    self.host_pool.put(h, k, v)
                fetched += len(pages)
            have = len(hashes)
            if view.get("complete"):
                total = view.get("total_blocks")
                complete = total is None or have >= int(total)
                if complete:
                    break
        self.prefetched_blocks += fetched
        if not complete:
            self.fallbacks += 1
        return {
            "complete": complete,
            "blocks": fetched,
            "total_blocks": total,
            "wall_s": time.monotonic() - t0,
        }
