"""LoRA adapter management: PEFT checkpoint loading + device slot bank.

The reference serves adapters through vLLM's LoRA support, driven over HTTP
by the operator (`loraadapter_controller.go:582-611` load/unload). Here the
TPU-native design keeps every loaded adapter in a **stacked device bank**:
for each targeted projection ``t`` the model params carry

    lora_a_<t>  [L, slots, in_dim,  r_max]
    lora_b_<t>  [L, slots, r_max, out_dim]

(slot 0 is all-zeros = "no adapter"). The forward pass gathers each batch
row's slot and adds ``scaling * (x @ A) @ B`` to the projection — so any mix
of adapters serves in ONE compiled step, no per-adapter recompilation and no
weight merging. Rank is padded to ``r_max`` with zeros (exact math).

Checkpoint format: a local directory in PEFT layout — ``adapter_config.json``
(r, lora_alpha, target_modules) + ``adapter_model.safetensors`` with keys
``...layers.{i}.self_attn.q_proj.lora_A.weight`` [r, in] / ``lora_B.weight``
[out, r]. Downloading from HF/S3/HTTP is the sidecar's job
(`scripts/adapter_downloader.py`, reference `docker/Dockerfile.sidecar`).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..logging_utils import init_logger

logger = init_logger(__name__)

# HF module name -> our stacked-param name (matches llama._HF_LAYER_MAP).
TARGETS = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
}


@dataclasses.dataclass
class LoadedAdapter:
    name: str
    slot: int
    rank: int
    scaling: float
    path: str


class LoraManager:
    """Host-side slot registry; the runner owns the device bank arrays."""

    def __init__(self, model_cfg, max_loras: int, max_rank: int,
                 adapter_dir: str = "/adapters"):
        self.model_cfg = model_cfg
        self.max_loras = max_loras
        self.max_rank = max_rank
        self.adapter_dir = adapter_dir
        self._adapters: Dict[str, LoadedAdapter] = {}
        self._free_slots: List[int] = list(range(max_loras, 0, -1))  # 1-based
        self._lock = threading.Lock()

    # -- queries -----------------------------------------------------------

    def get(self, name: str) -> Optional[LoadedAdapter]:
        return self._adapters.get(name)

    def list_adapters(self) -> List[LoadedAdapter]:
        return sorted(self._adapters.values(), key=lambda a: a.slot)

    def bank_shapes(self) -> Dict[str, Tuple[Tuple[int, ...], Tuple[int, ...]]]:
        """(A, B) array shapes per target (without the leading layer axis)."""
        cfg = self.model_cfg
        dims = {
            "wq": (cfg.hidden_size, cfg.q_size),
            "wk": (cfg.hidden_size, cfg.kv_size),
            "wv": (cfg.hidden_size, cfg.kv_size),
            "wo": (cfg.q_size, cfg.hidden_size),
        }
        out = {}
        for t, (din, dout) in dims.items():
            out[t] = (
                (self.max_loras + 1, din, self.max_rank),
                (self.max_loras + 1, self.max_rank, dout),
            )
        return out

    # -- load / unload -----------------------------------------------------

    def resolve_path(self, name: str, path: Optional[str]) -> str:
        if path:
            return path
        return os.path.join(self.adapter_dir, name)

    def load(self, name: str, path: Optional[str] = None):
        """Parse a PEFT checkpoint → (adapter, host arrays per target).

        Returns (LoadedAdapter, {target: (A [L, in, r_max], B [L, r_max, out])}).
        The caller (runner) installs the arrays into the device bank slot.
        """
        with self._lock:
            if name in self._adapters:
                return self._adapters[name], None  # already resident
            if not self._free_slots:
                raise RuntimeError(
                    f"no free LoRA slots (max_loras={self.max_loras})"
                )
            adapter_path = self.resolve_path(name, path)
            arrays, rank, scaling = self._parse_peft(adapter_path)
            slot = self._free_slots.pop()
            ad = LoadedAdapter(
                name=name, slot=slot, rank=rank, scaling=scaling,
                path=adapter_path,
            )
            self._adapters[name] = ad
            logger.info(
                "loaded LoRA %r (rank %d, scaling %.3f) into slot %d",
                name, rank, scaling, slot,
            )
            return ad, arrays

    def unload(self, name: str) -> Optional[LoadedAdapter]:
        """Remove the name from the registry. The slot is NOT freed here —
        in-flight sequences may still reference it; the engine calls
        :meth:`release_slot` once the last such sequence drains (zeroing and
        reusing the slot earlier would silently swap the weights under a
        running request)."""
        with self._lock:
            return self._adapters.pop(name, None)

    def release_slot(self, slot: int) -> None:
        with self._lock:
            if slot not in self._free_slots:
                self._free_slots.append(slot)

    # -- PEFT parsing ------------------------------------------------------

    def _parse_peft(self, path: str):
        from safetensors import safe_open

        cfg_path = os.path.join(path, "adapter_config.json")
        st_path = os.path.join(path, "adapter_model.safetensors")
        if not os.path.isfile(cfg_path) or not os.path.isfile(st_path):
            raise FileNotFoundError(
                f"not a PEFT adapter dir (need adapter_config.json + "
                f"adapter_model.safetensors): {path}"
            )
        with open(cfg_path) as f:
            acfg = json.load(f)
        rank = int(acfg.get("r", 8))
        alpha = float(acfg.get("lora_alpha", rank))
        scaling = alpha / rank
        if rank > self.max_rank:
            raise ValueError(
                f"adapter rank {rank} exceeds max_lora_rank={self.max_rank}"
            )

        L = self.model_cfg.num_layers
        shapes = self.bank_shapes()
        arrays = {}
        for t, (a_shape, b_shape) in shapes.items():
            arrays[t] = (
                np.zeros((L,) + a_shape[1:], np.float32),
                np.zeros((L,) + b_shape[1:], np.float32),
            )

        found = 0
        with safe_open(st_path, framework="numpy") as f:
            keys = list(f.keys())
            for key in keys:
                # ...model.layers.{i}.self_attn.{q_proj}.lora_{A,B}.weight
                parts = key.split(".")
                try:
                    li = parts.index("layers")
                except ValueError:
                    continue
                layer = int(parts[li + 1])
                module = parts[li + 3] if parts[li + 2] == "self_attn" else None
                if module not in TARGETS or layer >= L:
                    continue
                ours = TARGETS[module]
                w = np.asarray(f.get_tensor(key), np.float32)
                if ".lora_A." in key:
                    # PEFT stores A as [r, in]; our forward is x @ A -> [.., r]
                    arrays[ours][0][layer, :, : w.shape[0]] = w.T
                    found += 1
                elif ".lora_B." in key:
                    # PEFT stores B as [out, r]
                    arrays[ours][1][layer, : w.shape[1], :] = w.T
                    found += 1
        if not found:
            raise ValueError(f"no LoRA tensors for {list(TARGETS)} in {st_path}")
        return arrays, rank, scaling
